"""Served-traffic spool + drift observability tests
(hydragnn_tpu/obs/spool.py + obs/drift.py): sketch math against numpy
references, HGC spool round-trip bit-parity (edge_occupancy included),
rotation / disk bound / atomic finalization, per-tenant attribution,
drift triggers firing on injected shift and staying quiet on clean
traffic, and the incident bundle carrying its drift report.

All CPU (conftest pins the 8-device virtual mesh); the one real-server
test reuses a smoke-sized flagship build so the file stays tier-1-fast.
"""

import json
import os

import numpy as np
import pytest

from hydragnn_tpu.obs.drift import (
    DriftMonitor,
    P2Quantile,
    RunningMoments,
    build_reference,
    hist_counts,
    load_reference,
    psi,
    validate_drift_report,
)
from hydragnn_tpu.obs.flight import FlightRecorder, read_flight_record
from hydragnn_tpu.obs.registry import MetricsRegistry
from hydragnn_tpu.obs.spool import (
    RequestSpool,
    list_shards,
    read_shard_manifest,
    read_spool,
    validate_spool_manifest,
)


# ---------------------------------------------------------------------------
# sketch math vs numpy references
# ---------------------------------------------------------------------------


def test_running_moments_matches_numpy():
    rng = np.random.default_rng(0)
    data = rng.normal(3.0, 2.0, size=(500, 4))
    mom = RunningMoments(4)
    for chunk in np.array_split(data, 13):
        mom.update(chunk)
    assert mom.count == 500
    np.testing.assert_allclose(mom.mean, data.mean(axis=0), rtol=1e-10)
    np.testing.assert_allclose(mom.variance, data.var(axis=0), rtol=1e-10)
    np.testing.assert_allclose(mom.std, data.std(axis=0), rtol=1e-10)


def test_running_moments_accepts_1d():
    mom = RunningMoments(1)
    mom.update(np.array([1.0, 2.0, 3.0]))
    np.testing.assert_allclose(mom.mean, [2.0])


def test_p2_quantile_exact_small_then_approximate():
    est = P2Quantile(0.5)
    for v in (5.0, 1.0, 3.0):
        est.add(v)
    assert est.value == 3.0  # exact while <= 5 observations
    rng = np.random.default_rng(1)
    data = rng.normal(0.0, 1.0, size=5000)
    ests = {q: P2Quantile(q) for q in (0.05, 0.5, 0.95)}
    for v in data:
        for est in ests.values():
            est.add(v)
    for q, est in ests.items():
        assert abs(est.value - np.quantile(data, q)) < 0.06


def test_psi_zero_identical_positive_on_shift():
    ref = [0.25, 0.25, 0.25, 0.25]
    assert psi(ref, ref) == pytest.approx(0.0)
    shifted = psi(ref, [0.7, 0.2, 0.05, 0.05])
    assert shifted > 0.3
    # symmetric-ish and finite even with empty bins on one side
    assert np.isfinite(psi(ref, [1.0, 0.0, 0.0, 0.0]))


def test_hist_counts_partitions_and_keeps_top_edge_inner():
    edges = np.linspace(0.0, 1.0, 5)
    v = np.array([-0.5, 0.0, 0.4, 1.0, 1.0, 2.0])
    counts = hist_counts(v, edges)
    assert counts.sum() == len(v)
    assert counts[0] == 1  # underflow
    assert counts[-1] == 1  # strict overflow only
    # values exactly at the top edge stay in the last inner bin — the
    # reference fracs use np.histogram's closed right edge and discrete
    # features put real mass exactly at the reference max
    assert counts[-2] == 2


# ---------------------------------------------------------------------------
# reference window build / load
# ---------------------------------------------------------------------------


def _toy_samples(n=12, nodes=6, shift=0.0, seed=0):
    from hydragnn_tpu.data.dataset import GraphSample

    rng = np.random.default_rng(seed)
    out = []
    for i in range(n):
        x = (rng.normal(0.0, 1.0, size=(nodes, 2)) + shift).astype(np.float32)
        ei = np.stack(
            [np.arange(nodes), (np.arange(nodes) + 1) % nodes]
        ).astype(np.int32)
        out.append(
            GraphSample(
                x=x,
                pos=rng.normal(size=(nodes, 3)).astype(np.float32),
                edge_index=ei,
                graph_targets={"energy": np.float32(rng.normal())},
                node_targets={
                    "forces": rng.normal(size=(nodes, 1)).astype(np.float32)
                },
            )
        )
    return out


def test_build_reference_stats_and_errors(tmp_path):
    samples = _toy_samples()
    ref = build_reference(samples, head_names=["energy", "forces"])
    assert ref["schema"] == 1
    assert len(ref["feature"]["channels"]) == 2
    assert set(ref["heads"]) == {"energy", "forces"}
    ch = ref["feature"]["channels"][0]
    xs = np.concatenate([np.asarray(s.x) for s in samples])[:, 0]
    assert ch["mean"] == pytest.approx(float(xs.mean()), rel=1e-6)
    assert ch["std"] == pytest.approx(float(xs.std()), rel=1e-6)
    with pytest.raises(ValueError):
        build_reference([])


def test_load_reference_json_and_flight(tmp_path):
    ref = build_reference(_toy_samples())
    path = tmp_path / "ref.json"
    path.write_text(json.dumps(ref))
    loaded = load_reference(str(path))
    assert loaded["feature"]["channels"][0]["mean"] == pytest.approx(
        ref["feature"]["channels"][0]["mean"]
    )
    # flight-record form: the run_start.manifest.stats block
    fpath = tmp_path / "flight.jsonl"
    fr = FlightRecorder(str(fpath))
    fr.start_run({"stats": ref})
    fr.end_run("completed")
    assert load_reference(str(fpath))["num_rows"] == ref["num_rows"]
    with pytest.raises(FileNotFoundError):
        load_reference(str(tmp_path / "missing.json"))
    bad = tmp_path / "bad.json"
    bad.write_text(json.dumps({"schema": 99}))
    with pytest.raises(ValueError):
        load_reference(str(bad))


# ---------------------------------------------------------------------------
# drift monitor: quiet on clean, loud on shift
# ---------------------------------------------------------------------------


def _monitor(ref, registry=None, **kw):
    registry = registry or MetricsRegistry(enabled=True)
    kw.setdefault("min_count", 32)
    return DriftMonitor(ref, registry, **kw), registry


def _feed(monitor, samples, head_vals=None, shift=0.0):
    for i, s in enumerate(samples):
        preds = {}
        if head_vals is not None:
            preds = {name: vals[i] for name, vals in head_vals.items()}
        monitor.observe(np.asarray(s.x) + shift, preds)


def test_feature_drift_quiet_then_fires():
    samples = _toy_samples(n=40)
    ref = build_reference(samples)
    mon, reg = _monitor(ref)
    _feed(mon, samples)
    clean_psi = max(mon.feature_psi())
    assert clean_psi < 0.1
    assert reg.gauge("serve.drift.feature_psi").value < 0.25

    mon2, reg2 = _monitor(ref)
    _feed(mon2, samples, shift=5.0)
    assert max(mon2.feature_psi()) > 1.0
    assert reg2.gauge("serve.drift.feature_psi").value > 1.0
    assert max(mon2.feature_qshift()) > 3.0


def test_warmup_guard_keeps_gauges_zero():
    samples = _toy_samples(n=40)
    ref = build_reference(samples)
    mon, reg = _monitor(ref, min_count=10_000)
    _feed(mon, samples, shift=5.0)  # shifted, but below min_count rows
    assert reg.gauge("serve.drift.feature_psi").value == 0.0
    assert reg.gauge("serve.drift.feature_rows").value > 0


def test_channel_mismatch_raises():
    ref = build_reference(_toy_samples())
    mon, _ = _monitor(ref)
    with pytest.raises(ValueError):
        mon.observe(np.zeros((4, 7)), {})


def test_pred_drift_self_baseline_mid_session_shift():
    samples = _toy_samples(n=200)
    ref = build_reference(samples)
    rng = np.random.default_rng(2)
    stable = rng.normal(0.0, 1.0, size=200)
    mon, reg = _monitor(ref, min_count=32)
    # 100 stable requests: baseline freezes, live window matches it
    _feed(mon, samples[:100], head_vals={"energy": stable[:100]})
    assert max(mon.head_psi().values()) < 0.25
    assert reg.gauge("serve.drift.pred_psi").value < 0.25
    # mid-session the prediction distribution jumps
    _feed(mon, samples[100:], head_vals={"energy": stable[100:] + 8.0})
    assert max(mon.head_psi().values()) > 1.0
    assert reg.gauge("serve.drift.pred_psi").value > 1.0


def test_error_drift_track():
    ref = build_reference(_toy_samples(), head_names=["energy"])
    mon, reg = _monitor(ref, min_labeled=4)
    scale = ref["heads"]["energy"]["scale"]
    for _ in range(8):
        mon.observe_labeled("energy", np.array([10.0 * scale]), np.array([0.0]))
    assert mon.error_scores()["energy"] > 3.0
    assert reg.gauge("serve.drift.error_score").value > 3.0


def test_drift_report_validates_and_rejects_garbage():
    samples = _toy_samples(n=40)
    mon, _ = _monitor(build_reference(samples))
    _feed(mon, samples)
    report = mon.report()
    assert validate_drift_report(report) == []
    assert report["counts"]["feature_rows"] == mon.feature_rows
    assert validate_drift_report({"schema": 0})  # non-empty problems
    broken = dict(report)
    broken.pop("feature")
    assert any("feature" in p for p in validate_drift_report(broken))


def test_drift_trigger_rules_fire_and_stay_quiet(tmp_path):
    from hydragnn_tpu.obs.triggers import (
        RULE_KINDS,
        TriggerEngine,
        TriggerRule,
    )

    assert {"feature_drift", "pred_drift", "error_drift"} <= set(RULE_KINDS)
    samples = _toy_samples(n=40)
    ref = build_reference(samples)
    reg = MetricsRegistry(enabled=True)
    rule = TriggerRule(
        "serve_feature_drift", "feature_drift", "serve.drift.feature_psi", 0.25
    )
    engine = TriggerEngine([rule], registry=reg)
    mon, _ = _monitor(ref, registry=reg)
    _feed(mon, samples)
    assert engine.evaluate() == []  # clean: no verdicts
    _feed(mon, samples, shift=5.0)
    verdicts = engine.evaluate()
    assert [v.kind for v in verdicts] == ["feature_drift"]
    assert verdicts[0].observed > 0.25
    assert "feature_rows" in verdicts[0].detail


# ---------------------------------------------------------------------------
# request spool: HGC round-trip, rotation, disk bound, crash safety
# ---------------------------------------------------------------------------


def _request_dict(sample):
    ei = np.asarray(sample.edge_index)
    return {
        "x": np.asarray(sample.x),
        "pos": np.asarray(sample.pos),
        "senders": ei[0],
        "receivers": ei[1],
    }


def _result_for(sample, seed=0):
    rng = np.random.default_rng(seed)
    n = np.asarray(sample.x).shape[0]
    return {
        "energy": rng.normal(size=(1,)).astype(np.float32),
        "forces": rng.normal(size=(n, 1)).astype(np.float32),
    }


_HEAD_KINDS = {"energy": "graph", "forces": "node"}


def test_spool_roundtrip_bit_parity(tmp_path):
    samples = _toy_samples(n=6)
    spool = RequestSpool(
        str(tmp_path / "spool"),
        sample_every=1,
        max_mb=8.0,
        model_fingerprint="fp-test",
        head_kinds=_HEAD_KINDS,
    )
    for i, s in enumerate(samples):
        took = spool.offer(
            _request_dict(s), _result_for(s, i),
            trace=f"tr-{i}", tenant="acme", seq=i,
        )
        assert took
    spool.finalize()
    back = list(read_spool(str(tmp_path / "spool")))
    assert len(back) == len(samples)
    back.sort(key=lambda s: s.meta["spool"]["seq"])
    for i, (orig, got) in enumerate(zip(samples, back)):
        # the HGC writer stores x/pos as f32 — parity vs the f32 cast
        assert np.array_equal(np.asarray(got.x), np.asarray(orig.x, np.float32))
        assert np.array_equal(
            np.asarray(got.pos), np.asarray(orig.pos, np.float32)
        )
        assert np.array_equal(
            np.asarray(got.edge_index), np.asarray(orig.edge_index)
        )
        want = _result_for(orig, i)
        np.testing.assert_array_equal(
            got.graph_targets["energy"], want["energy"]
        )
        np.testing.assert_array_equal(got.node_targets["forces"], want["forces"])
        blk = got.meta["spool"]
        assert blk["trace"] == f"tr-{i}"
        assert blk["tenant"] == "acme"
        assert blk["model_fingerprint"] == "fp-test"


def test_spooled_shard_batches_like_the_original(tmp_path):
    """edge_occupancy parity: a spooled shard re-entering the batcher
    produces bit-identical padded batches (the retraining contract)."""
    from hydragnn_tpu.graph.batch import batch_graphs
    from hydragnn_tpu.serve.server import request_to_dict

    samples = _toy_samples(n=4)
    spool = RequestSpool(
        str(tmp_path / "spool"), sample_every=1, head_kinds=_HEAD_KINDS
    )
    for i, s in enumerate(samples):
        spool.offer(_request_dict(s), _result_for(s, i), seq=i)
    spool.finalize()
    back = sorted(
        read_spool(str(tmp_path / "spool")),
        key=lambda s: s.meta["spool"]["seq"],
    )
    want = batch_graphs([request_to_dict(s) for s in samples])
    got = batch_graphs([request_to_dict(s) for s in back])
    assert int(want.edge_occupancy) == int(got.edge_occupancy)
    np.testing.assert_array_equal(np.asarray(want.nodes), np.asarray(got.nodes))
    np.testing.assert_array_equal(
        np.asarray(want.senders), np.asarray(got.senders)
    )


def test_spool_sampling_rotation_and_disk_bound(tmp_path):
    samples = _toy_samples(n=32, nodes=64)  # ~2KB/sample: forces rotations
    events = []

    class _Flight:
        def record(self, kind, **fields):
            events.append({"kind": kind, **fields})

    spool = RequestSpool(
        str(tmp_path / "spool"),
        sample_every=2,
        max_mb=0.02,  # ~2 shards' worth: forces LRU eviction
        shard_mb=0.01,
        head_kinds=_HEAD_KINDS,
        flight=_Flight(),
    )
    for i, s in enumerate(samples):
        spool.offer(_request_dict(s), _result_for(s, i), seq=i)
    summary = spool.finalize()
    assert summary["seen"] == 32
    assert summary["spooled"] == 16  # every 2nd request
    assert summary["rotations"] >= 2
    assert summary["evicted"] >= 1
    shards = list_shards(str(tmp_path / "spool"))
    assert shards  # evicted down to the bound, never to nothing
    total = summary["bytes"]
    assert total <= 0.02 * 1024 * 1024 or len(shards) == 1
    rot = [e for e in events if e["kind"] == "spool_rotate"]
    assert len(rot) == summary["rotations"]
    assert all("total_bytes" in e and "shard" in e for e in rot)
    # surviving shards hold the HIGHEST seq numbers (LRU evicts oldest)
    mans = [read_shard_manifest(s) for s in shards]
    assert validate_spool_manifest(mans[-1]) == []
    assert mans[-1]["seq_range"][1] == 30  # last sampled seq


def test_spool_atomic_finalize_sweeps_crash_debris(tmp_path):
    root = tmp_path / "spool"
    spool = RequestSpool(str(root), sample_every=1, head_kinds=_HEAD_KINDS)
    s = _toy_samples(n=1)[0]
    spool.offer(_request_dict(s), _result_for(s), seq=0)
    spool.finalize()
    # simulate a crash mid-rotation: a dot-dir with partial contents
    debris = root / ".shard-000099.tmp-12345"
    debris.mkdir()
    (debris / "junk").write_text("partial")
    # readers never see it...
    assert all(".shard" not in p for p in list_shards(str(root)))
    # ...and the next spool construction sweeps it
    RequestSpool(str(root), sample_every=1, head_kinds=_HEAD_KINDS)
    assert not debris.exists()


def test_spool_per_tenant_attribution(tmp_path):
    samples = _toy_samples(n=4)
    spool = RequestSpool(
        str(tmp_path / "spool"), sample_every=1, head_kinds=_HEAD_KINDS
    )
    tenants = ["acme", "globex", "acme", "initech"]
    for i, (s, t) in enumerate(zip(samples, tenants)):
        spool.offer(_request_dict(s), _result_for(s, i), tenant=t, seq=i)
    spool.finalize()
    (shard,) = list_shards(str(tmp_path / "spool"))
    man = read_shard_manifest(shard)
    assert man["tenants"] == sorted(set(tenants))
    by_tenant = {}
    for got in read_spool(str(tmp_path / "spool")):
        by_tenant.setdefault(got.meta["spool"]["tenant"], []).append(got)
    assert {t: len(v) for t, v in by_tenant.items()} == {
        "acme": 2, "globex": 1, "initech": 1,
    }


def test_validate_spool_manifest_rejects_garbage():
    assert validate_spool_manifest({"schema": 1}) != []
    assert any(
        "num_samples" in p
        for p in validate_spool_manifest(
            {
                "schema": 1, "shard": "s", "num_samples": 0,
                "model_fingerprint": "", "sample_every": 1,
                "tenants": [], "seq_range": [0, 0], "t_range": [0, 0],
            }
        )
    )


# ---------------------------------------------------------------------------
# knobs + lint parity
# ---------------------------------------------------------------------------


def test_spool_drift_knobs_documented():
    from hydragnn_tpu.utils import knobs

    names = set(knobs.KNOBS)
    for knob in (
        "HYDRAGNN_SPOOL",
        "HYDRAGNN_SPOOL_SAMPLE",
        "HYDRAGNN_SPOOL_MAX_MB",
        "HYDRAGNN_DRIFT_REF",
        "HYDRAGNN_INJECT_DRIFT",
    ):
        assert knob in names
    doc = open(
        os.path.join(os.path.dirname(__file__), "..", "docs", "KNOBS.md")
    ).read()
    assert "HYDRAGNN_SPOOL" in doc and "HYDRAGNN_DRIFT_REF" in doc


def test_artifact_linter_knows_spool_and_drift_schemas(tmp_path):
    from hydragnn_tpu.lint.artifacts import RUNTIME_SCHEMAS

    assert "drift_report.json" in RUNTIME_SCHEMAS
    assert "spool_manifest.json" in RUNTIME_SCHEMAS
    label, check = RUNTIME_SCHEMAS["spool_manifest.json"]
    samples = _toy_samples(n=2)
    spool = RequestSpool(
        str(tmp_path / "spool"), sample_every=1, head_kinds=_HEAD_KINDS
    )
    for i, s in enumerate(samples):
        spool.offer(_request_dict(s), _result_for(s, i), seq=i)
    spool.finalize()
    (shard,) = list_shards(str(tmp_path / "spool"))
    assert check(read_shard_manifest(shard)) == []
    mon, _ = _monitor(build_reference(samples))
    _feed(mon, samples)
    _, check_report = RUNTIME_SCHEMAS["drift_report.json"]
    assert check_report(json.loads(json.dumps(mon.report()))) == []


# ---------------------------------------------------------------------------
# full server: spool + drift armed, injected shift -> one incident
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def flagship_setup():
    from hydragnn_tpu.flagship import build_flagship
    from hydragnn_tpu.serve import ModelRegistry

    _, model, variables, loader = build_flagship(
        n_samples=24,
        hidden_dim=8,
        num_conv_layers=2,
        batch_size=4,
        unit_cells=(2, 3),
    )
    registry = ModelRegistry()
    served = registry.register("drift-smoke", model, variables)
    return served, list(loader.all_samples)


@pytest.mark.slow
def test_server_drift_incident_end_to_end(flagship_setup, tmp_path, monkeypatch):
    from hydragnn_tpu.obs.triggers import (
        list_incidents,
        validate_incident_bundle,
    )
    from hydragnn_tpu.serve import ModelServer, ServeConfig

    served, samples = flagship_setup
    ref = build_reference(samples)
    ref_path = tmp_path / "ref.json"
    ref_path.write_text(json.dumps(ref))
    monkeypatch.setenv("HYDRAGNN_INJECT_DRIFT", "5.0")
    flight_path = tmp_path / "flight.jsonl"
    cfg = ServeConfig(
        max_batch=4,
        max_delay_ms=5.0,
        slo_p99_ms=60_000.0,
        trigger_eval_every_s=0.05,
        incident_dir=str(tmp_path / "inc"),
        spool=True,
        spool_sample=1,
        spool_dir=str(tmp_path / "spool"),
        drift_ref=str(ref_path),
        drift_min_count=16,
    )
    with ModelServer(
        served, samples, cfg, flight=FlightRecorder(str(flight_path))
    ) as server:
        for s in samples[:20]:
            server.predict(s, timeout=120)
        import time

        time.sleep(0.3)
    events = read_flight_record(str(flight_path))
    start = next(e for e in events if e["kind"] == "run_start")
    assert start["manifest"]["spool"]["enabled"]
    assert start["manifest"]["drift"]["armed"]
    end = next(e for e in reversed(events) if e["kind"] == "run_end")
    assert end["spool"]["spooled"] >= 1
    assert "overhead_frac" in end["spool"]
    assert end["drift"]["feature_psi_max"] > 0.25
    drifts = [e for e in events if e["kind"] == "drift"]
    assert drifts and drifts[0]["rule_kind"] == "feature_drift"
    bundles = list_incidents(str(tmp_path / "inc"))
    assert len(bundles) == 1
    assert validate_incident_bundle(bundles[0]) == []
    report = json.load(open(os.path.join(bundles[0], "drift_report.json")))
    assert validate_drift_report(report) == []
    assert report["trigger"]["kind"] == "feature_drift"
    assert report["spool_window"]["dir"] == str(tmp_path / "spool")
    # the spooled shards reload through the container reader
    assert len(list(read_spool(str(tmp_path / "spool")))) >= 1
