"""Model-level introspection tests (hydragnn_tpu/obs/introspect.py):
per-head gradient norm / conflict-cosine / update-ratio math against a
pure-numpy reference on a tiny 2-head model, per-head MAE/RMSE against
numpy, sampling discipline (zero unexpected recompiles, no per-step
host syncs, telemetry-off bit-identical training), the hardware ledger
degradations, flight-record v1/v2 forward compat, and the anomaly
heuristics the --heads report renders."""

import json

import numpy as np
import jax
import jax.numpy as jnp
import optax
import pytest

from hydragnn_tpu.graph import batch_graphs
from hydragnn_tpu.models import ModelConfig, create_model, model_loss
from hydragnn_tpu.obs import (
    CompileMonitor,
    FlightRecorder,
    HardwareLedger,
    HeadDiagnostics,
    collect_head_series,
    flag_anomalies,
    flight_record_warnings,
    make_diagnostics_step,
    per_head_error_metrics,
    read_flight_record,
    validate_flight_record,
)
from hydragnn_tpu.train import create_train_state, make_train_step


def _tiny_two_head(seed: int = 0):
    """A 2-head (graph energy + node charge) GIN on a handful of ring
    graphs — small enough that a numpy reference over flattened
    gradients is exact and fast."""
    rng = np.random.RandomState(seed)
    graphs = []
    for gi in range(6):
        n = 4 + gi % 3
        s = np.concatenate([np.arange(n), np.roll(np.arange(n), 1)]).astype(np.int32)
        r = np.concatenate([np.roll(np.arange(n), 1), np.arange(n)]).astype(np.int32)
        graphs.append(
            {
                "x": rng.rand(n, 2).astype(np.float32),
                "senders": s,
                "receivers": r,
                "pos": rng.rand(n, 3).astype(np.float32),
                "graph_targets": {"energy": np.asarray([rng.rand()], np.float32)},
                "node_targets": {"charge": rng.rand(n, 1).astype(np.float32)},
            }
        )
    batch = batch_graphs(graphs)
    cfg = ModelConfig(
        model_type="GIN",
        input_dim=2,
        hidden_dim=8,
        output_dim=(1, 1),
        output_type=("graph", "node"),
        output_names=("energy", "charge"),
        task_weights=(2.0, 1.0),
        num_conv_layers=2,
        graph_num_sharedlayers=1,
        graph_dim_sharedlayers=8,
        graph_num_headlayers=1,
        graph_dim_headlayers=(8,),
        node_num_headlayers=1,
        node_dim_headlayers=(8,),
    )
    model, variables = create_model(cfg, batch)
    return cfg, model, variables, batch


def _flatten_tree(tree) -> np.ndarray:
    return np.concatenate(
        [np.asarray(leaf, np.float64).ravel() for leaf in jax.tree_util.tree_leaves(tree)]
    )


# ---------------------------------------------------------------------------
# the diagnostics math vs a pure-numpy reference
# ---------------------------------------------------------------------------


def test_diagnostics_step_matches_numpy_reference():
    cfg, model, variables, batch = _tiny_two_head()
    tx = optax.adam(1e-3)
    state = create_train_state(variables, tx)
    diag_fn = make_diagnostics_step(model, tx)
    out = jax.device_get(diag_fn(state, batch))

    # independent per-head gradients: jax.grad of each scalar head loss
    # (a different autodiff path than the shared-vjp one-hot pulls),
    # flattened to numpy where norms/cosine/ratio are recomputed
    _, dropout_rng = jax.random.split(state.rng)

    def head_loss(params, ihead):
        outputs, _ = model.apply(
            {"params": params, "batch_stats": state.batch_stats},
            batch,
            train=True,
            mutable=["batch_stats"],
            rngs={"dropout": dropout_rng},
        )
        outputs = [o.astype(jnp.float32) for o in outputs]
        _, tasks = model_loss(cfg, outputs, batch)
        return tasks[ihead]

    flats = []
    for ihead in range(2):
        g = jax.grad(lambda p, i=ihead: head_loss(p, i))(state.params)
        flats.append(_flatten_tree(g))
    ref_norms = [float(np.linalg.norm(f)) for f in flats]
    ref_cos = float(flats[0] @ flats[1] / (ref_norms[0] * ref_norms[1]))

    np.testing.assert_allclose(out["grad_norms"], ref_norms, rtol=1e-4)
    cos = np.asarray(out["cosine"])
    assert cos.shape == (2, 2)
    np.testing.assert_allclose(np.diagonal(cos), [1.0, 1.0], atol=1e-5)
    np.testing.assert_allclose(cos[0, 1], ref_cos, rtol=1e-3, atol=1e-5)
    np.testing.assert_allclose(cos[1, 0], ref_cos, rtol=1e-3, atol=1e-5)

    # total gradient = weight-vector cotangent pull; update ratio from
    # an independent optax update over the numpy-recombined total
    w = np.asarray(cfg.normalized_weights, np.float64)
    ref_total = float(np.linalg.norm(w[0] * flats[0] + w[1] * flats[1]))
    np.testing.assert_allclose(out["grad_norm_total"], ref_total, rtol=1e-4)

    total_tree = jax.grad(
        lambda p: w[0] * head_loss(p, 0) + w[1] * head_loss(p, 1)
    )(state.params)
    updates, _ = tx.update(total_tree, state.opt_state, state.params)
    ref_update = float(np.linalg.norm(_flatten_tree(updates)))
    ref_param = float(np.linalg.norm(_flatten_tree(state.params)))
    np.testing.assert_allclose(out["update_norm"], ref_update, rtol=1e-4)
    np.testing.assert_allclose(out["param_norm"], ref_param, rtol=1e-5)
    np.testing.assert_allclose(out["update_ratio"], ref_update / ref_param, rtol=1e-4)

    # per-head losses come along for free (the forward's task vector)
    np.testing.assert_allclose(
        out["tasks_loss"][0], float(head_loss(state.params, 0)), rtol=1e-5
    )


def test_per_head_error_metrics_matches_numpy():
    rng = np.random.RandomState(1)
    trues = [rng.rand(17, 1), rng.rand(40, 1)]
    preds = [rng.rand(17, 1), rng.rand(40, 1)]
    m = per_head_error_metrics(trues, preds, ["energy", "charge"])
    for name, t, p in zip(["energy", "charge"], trues, preds):
        d = (p - t).ravel()
        assert m[name]["count"] == t.size
        np.testing.assert_allclose(m[name]["mae"], np.abs(d).mean(), rtol=1e-12)
        np.testing.assert_allclose(
            m[name]["rmse"], np.sqrt((d * d).mean()), rtol=1e-12
        )
    empty = per_head_error_metrics([np.zeros((0, 1))], [np.zeros((0, 1))], ["x"])
    assert empty["x"] == {"mae": None, "rmse": None, "count": 0}


# ---------------------------------------------------------------------------
# sampling discipline: separate executable, compiled once, no per-step syncs
# ---------------------------------------------------------------------------


def test_diagnostics_zero_unexpected_recompiles_and_no_per_step_syncs(monkeypatch):
    """The hot-path contract: diagnostics at default sampling add ONE
    new executable compiled on the first sampled step and nothing after;
    non-sampled and sampled steps alike perform no host sync (the
    snapshot at the epoch boundary is the only D2H)."""
    cfg, model, variables, batch = _tiny_two_head()
    tx = optax.adam(1e-3)
    state = create_train_state(variables, tx)
    step, diag_fn = make_train_step(model, tx, diagnostics=True)
    diag = HeadDiagnostics(diag_fn, cfg.output_names, every=3)

    with CompileMonitor() as mon:
        diag.maybe_sample(state, batch)  # sampled step 0: diag compiles
        state, loss, _ = step(state, batch)  # train step compiles
        jax.block_until_ready(loss)
        assert mon.count >= 1
        mon.mark("warm")

        def _boom(*a, **kw):  # pragma: no cover - must never run
            raise AssertionError("introspection must not sync per step")

        monkeypatch.setattr(jax, "block_until_ready", _boom)
        monkeypatch.setattr(jax, "device_get", _boom)
        for _ in range(5):  # steps 1..5: step 3 re-samples (warm cache)
            diag.maybe_sample(state, batch)
            state, loss, _ = step(state, batch)
        monkeypatch.undo()

        jax.block_until_ready(loss)
        assert mon.count_since("warm") == 0, (
            "a diagnostics-enabled loop recompiled after the first step"
        )

    snap = diag.epoch_snapshot()
    assert snap is not None and snap["available"]
    assert set(snap["grad_norm"]) == {"energy", "charge"}
    assert snap["sampled_step"] == 3
    # snapshot drains the pending sample: nothing to report until the
    # next sampled step
    assert diag.epoch_snapshot() is None


def test_telemetry_disabled_training_is_bit_identical(tmp_path, monkeypatch):
    """HYDRAGNN_TELEMETRY=0 must leave the training computation
    untouched: same config + data + seeds with telemetry (and its
    default-on diagnostics) fully enabled vs fully disabled produce
    bit-identical final parameters."""
    from hydragnn_tpu.api import run_training
    from hydragnn_tpu.data.synthetic import deterministic_graph_data
    from hydragnn_tpu.flagship import flagship_config
    from hydragnn_tpu.obs import reset_registry

    def _run(log_dir, telemetry: bool):
        if not telemetry:
            monkeypatch.setenv("HYDRAGNN_TELEMETRY", "0")
        else:
            monkeypatch.delenv("HYDRAGNN_TELEMETRY", raising=False)
            # the on-run must exercise the full introspection path the
            # suite's conftest otherwise disables
            monkeypatch.setenv("HYDRAGNN_DIAGNOSTICS", "1")
        reset_registry()
        try:
            cfg = flagship_config(
                hidden_dim=8, num_conv_layers=2, batch_size=5, num_epoch=1
            )
            samples = deterministic_graph_data(
                number_configurations=20,
                unit_cell_x_range=(2, 3),
                unit_cell_y_range=(2, 3),
                unit_cell_z_range=(2, 3),
                seed=0,
            )
            _, state, _, _ = run_training(cfg, samples=samples, log_dir=str(log_dir))
            return jax.device_get(state.params)
        finally:
            monkeypatch.delenv("HYDRAGNN_TELEMETRY", raising=False)
            reset_registry()

    p_on = _run(tmp_path / "on", telemetry=True)
    p_off = _run(tmp_path / "off", telemetry=False)
    flat_on, flat_off = _flatten_tree(p_on), _flatten_tree(p_off)
    assert flat_on.shape == flat_off.shape
    np.testing.assert_array_equal(flat_on, flat_off)


# ---------------------------------------------------------------------------
# hardware-efficiency ledger
# ---------------------------------------------------------------------------


def test_hardware_ledger_prices_a_jitted_step():
    f = jax.jit(lambda x: (x @ x).sum())
    ledger = HardwareLedger.from_step(f, (jnp.ones((16, 16)),))
    assert ledger.available
    man = ledger.manifest()
    assert man["available"] and man["flops_per_step"] > 0
    assert "peak_bf16_tflops" in man  # None on CPU, a number on TPU
    rec = ledger.epoch_record(steps=10, wall_s=0.25)
    assert rec["available"] and rec["achieved_tflops"] > 0
    assert rec["steps"] == 10 and rec["train_wall_s"] == 0.25
    # MFU needs a known chip peak; memory needs backend memory_stats —
    # both degrade to explicit unavailability, never a crash
    assert "mfu" in rec
    assert "available" in rec["memory"]
    summary = ledger.run_summary()
    assert summary["available"]


def test_hardware_ledger_degrades_on_unlowerable_step():
    ledger = HardwareLedger.from_step(lambda x: x, (1,))
    assert not ledger.available
    assert ledger.manifest()["available"] is False
    assert ledger.manifest()["reason"].startswith("lowering_failed")
    rec = ledger.epoch_record(steps=4, wall_s=1.0)
    assert rec["available"] is False and "achieved_tflops" not in rec
    assert "available" in rec["memory"]


# ---------------------------------------------------------------------------
# flight schema v2 + forward compat
# ---------------------------------------------------------------------------


def test_flight_v1_records_still_validate(tmp_path):
    path = str(tmp_path / "v1.jsonl")
    with open(path, "w") as f:
        f.write(
            json.dumps(
                {
                    "v": 1,
                    "kind": "run_start",
                    "t": 1.0,
                    "rank": 0,
                    "manifest": {
                        "jax_version": "0.4",
                        "backend": "cpu",
                        "num_processes": 1,
                    },
                }
            )
            + "\n"
        )
        f.write(
            json.dumps(
                {
                    "v": 1,
                    "kind": "epoch",
                    "t": 2.0,
                    "rank": 0,
                    "epoch": 0,
                    "train_loss": 1.0,
                    "val_loss": 1.1,
                    "train_tasks": [0.5, 0.5],  # v1 positional lists
                }
            )
            + "\n"
        )
        f.write(
            json.dumps(
                {"v": 1, "kind": "run_end", "t": 3.0, "rank": 0, "status": "completed"}
            )
            + "\n"
        )
    assert validate_flight_record(path, require_complete=True) == []
    assert flight_record_warnings(path) == []
    # the head-series reader accepts v1 positional task lists
    series = collect_head_series(read_flight_record(path))
    assert series["names"] == ["task0", "task1"]
    assert series["train_loss"]["task0"] == [0.5]


def test_flight_unknown_kinds_and_newer_versions_warn_not_fail(tmp_path):
    path = str(tmp_path / "future.jsonl")
    with FlightRecorder(path) as fr:
        fr.start_run({"run": "t"})
        fr.epoch(0, train_loss=1.0, val_loss=1.0)
        fr.end_run(status="completed")
    with open(path, "a") as f:
        f.write(
            json.dumps({"v": 2, "kind": "quantum_leap", "t": 4.0, "rank": 0}) + "\n"
        )
        f.write(
            json.dumps(
                {"v": 3, "kind": "run_end", "t": 5.0, "rank": 0, "status": "x"}
            )
            + "\n"
        )
    events = read_flight_record(path)
    assert validate_flight_record(events) == []  # accepted, not failed
    warnings = flight_record_warnings(events)
    assert any("unknown event kind 'quantum_leap'" in w for w in warnings)
    assert any("newer than this reader" in w for w in warnings)
    # a genuinely bogus version is still a validation problem
    bogus = [{"v": "two", "kind": "epoch", "t": 1.0, "rank": 0,
              "epoch": 0, "train_loss": 1.0, "val_loss": 1.0}]
    assert any("schema version" in p for p in validate_flight_record(bogus))


def test_current_writer_emits_v2(tmp_path):
    path = str(tmp_path / "now.jsonl")
    with FlightRecorder(path) as fr:
        fr.start_run({"run": "t"})
    assert read_flight_record(path)[0]["v"] == 2


# ---------------------------------------------------------------------------
# head-series extraction + anomaly heuristics (the --heads view's math)
# ---------------------------------------------------------------------------


def _series(**overrides):
    base = {
        "names": ["a", "b"],
        "epochs": [0, 1, 2, 3],
        "train_loss": {"a": [1.0, 1.0, 1.0, 1.0], "b": [1.0, 1.0, 1.0, 1.0]},
        "grad_norm": {"a": [1.0] * 4, "b": [1.0] * 4},
        "mae": {"a": [None] * 4, "b": [None] * 4},
        "rmse": {"a": [None] * 4, "b": [None] * 4},
        "cosine": [[[1.0, 0.5], [0.5, 1.0]]] * 4,
        "update_ratio": [0.01] * 4,
    }
    base.update(overrides)
    return base


def test_flag_anomalies_healthy_run_is_quiet():
    assert flag_anomalies(_series()) == []


def test_flag_anomalies_detects_all_three_classes():
    flags = flag_anomalies(
        _series(
            train_loss={"a": [1.0, 1.0, 1.0, 9.0], "b": [1.0] * 4},
            grad_norm={"a": [50.0] * 4, "b": [1.0] * 4},
            cosine=[[[1.0, -0.4], [-0.4, 1.0]]] * 4,
        )
    )
    assert any("loss spike" in f and "'a'" in f for f in flags)
    assert any("task conflict" in f for f in flags)
    assert any("gradient imbalance" in f and "50" in f for f in flags)


def test_flag_anomalies_ignores_transient_negatives():
    # one negative-cosine epoch out of four is a blip, not a conflict
    flags = flag_anomalies(
        _series(
            cosine=[[[1.0, -0.4], [-0.4, 1.0]]]
            + [[[1.0, 0.3], [0.3, 1.0]]] * 3
        )
    )
    assert not any("task conflict" in f for f in flags)


def test_collect_head_series_reads_v2_epoch_events():
    events = [
        {
            "kind": "epoch",
            "epoch": e,
            "train_tasks": {"energy": 1.0 / (e + 1), "charge": 0.5},
            "heads": {
                "names": ["energy", "charge"],
                "grad_norm": {"energy": 2.0, "charge": 1.0},
                "mae": {"energy": 0.1, "charge": 0.2},
                "rmse": {"energy": 0.2, "charge": 0.3},
                "cosine": [[1.0, 0.1], [0.1, 1.0]],
                "update_ratio": 0.005,
            },
        }
        for e in range(3)
    ]
    s = collect_head_series(events)
    assert s["names"] == ["energy", "charge"]
    assert s["train_loss"]["energy"] == [1.0, 0.5, pytest.approx(1 / 3)]
    assert s["grad_norm"]["charge"] == [1.0, 1.0, 1.0]
    assert s["mae"]["energy"] == [0.1, 0.1, 0.1]
    assert len(s["cosine"]) == 3 and s["update_ratio"] == [0.005] * 3


def test_obs_report_heads_view_renders(tmp_path, capsys):
    import os
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir, "tools"))
    try:
        import obs_report
    finally:
        sys.path.pop(0)

    path = str(tmp_path / "flight.jsonl")
    with FlightRecorder(path) as fr:
        fr.start_run({"run": "t", "head_names": ["energy", "charge"]})
        for ep in range(2):
            fr.epoch(
                ep,
                train_loss=1.0,
                val_loss=1.0,
                train_tasks={"energy": 0.6, "charge": 0.4},
                val_tasks={"energy": 0.7, "charge": 0.5},
                heads={
                    "names": ["energy", "charge"],
                    "available": True,
                    "grad_norm": {"energy": 2.0, "charge": 1.0},
                    "cosine": [[1.0, -0.3], [-0.3, 1.0]],
                    "update_ratio": 0.004,
                    "mae": {"energy": 0.1, "charge": 0.2},
                    "rmse": {"energy": 0.15, "charge": 0.25},
                },
                hw={
                    "available": True,
                    "achieved_tflops": 1.25,
                    "mfu": 0.41,
                    "memory": {"available": True, "peak_bytes_in_use": 123456},
                },
            )
        fr.end_run(status="completed")

    assert obs_report.main(["--heads", path]) == 0
    out = capsys.readouterr().out
    assert "task-conflict matrix" in out
    assert "energy" in out and "charge" in out
    assert "hardware-efficiency ledger" in out and "0.41" in out
    assert "task conflict" in out  # -0.3 in both epochs flags the pair


# ---------------------------------------------------------------------------
# the HeadDiagnostics sampler cadence
# ---------------------------------------------------------------------------


def test_head_diagnostics_sampling_cadence():
    calls = []

    def fake_fn(state, batch):
        calls.append(state)
        return {
            "tasks_loss": np.asarray([0.1, 0.2]),
            "grad_norms": np.asarray([1.0, 2.0]),
            "cosine": np.eye(2),
            "grad_norm_total": np.float32(2.0),
            "param_norm": np.float32(4.0),
            "update_norm": np.float32(0.1),
            "update_ratio": np.float32(0.025),
        }

    diag = HeadDiagnostics(fake_fn, ["a", "b"], every=4)
    for step in range(10):
        diag.maybe_sample(step, None)
    assert calls == [0, 4, 8]  # steps 0, 4, 8 sampled
    snap = diag.epoch_snapshot()
    assert snap["sampled_step"] == 8
    assert snap["grad_norm"] == {"a": 1.0, "b": 2.0}
    assert snap["update_ratio"] == pytest.approx(0.025)
