"""DistSampleStore tests: local lookup path, wire round-trip over a real
loopback TCP connection, LRU caching, ownership math.

Single-process pytest can't run a true 2-process store, so the wire path
is exercised by standing up a second store instance's server manually and
fetching through the client machinery (same protocol both ways). The
reference tests its DDStore path only implicitly through the 2-rank MPI CI
pass (SURVEY.md §4)."""

import socket
import struct

import numpy as np

from hydragnn_tpu.data.diststore import (
    DistSampleStore,
    _pack_sample,
    _recv_exact,
    _unpack_sample,
)
from hydragnn_tpu.data.ingest import prepare_dataset
from hydragnn_tpu.data.synthetic import deterministic_graph_data

from test_data_pipeline import base_config


def _built_samples(n=12, seed=9):
    cfg = base_config(multihead=True)
    samples = deterministic_graph_data(number_configurations=n, seed=seed)
    train, _, _, _, _ = prepare_dataset(samples, cfg)
    return train


def pytest_pack_unpack_roundtrip():
    s = _built_samples(4)[0]
    s2 = _unpack_sample(_pack_sample(s))
    np.testing.assert_array_equal(s.x, s2.x)
    np.testing.assert_array_equal(s.edge_index, s2.edge_index)
    for k in s.graph_targets:
        np.testing.assert_allclose(s.graph_targets[k], s2.graph_targets[k])


def pytest_local_store():
    samples = _built_samples(12)
    n = len(samples)
    store = DistSampleStore(samples)
    assert len(store) == n
    for i in (0, n // 2, n - 1):
        np.testing.assert_array_equal(store.get(i).x, samples[i].x)
    store.close()


def pytest_ownership_math():
    samples = _built_samples(8)[:4]
    store = DistSampleStore(samples, global_counts=[4, 6, 2])
    assert len(store) == 12
    assert store.owner_of(0) == 0
    assert store.owner_of(3) == 0
    assert store.owner_of(4) == 1
    assert store.owner_of(9) == 1
    assert store.owner_of(10) == 2
    store.close()


def pytest_remote_fetch_over_loopback():
    """Drive the real server thread + client protocol: store A owns global
    indices [0,4) locally; a hand-wired 'peer' server owns [4,8)."""
    local = _built_samples(8, seed=1)[:4]
    remote = _built_samples(8, seed=2)[:4]

    store = DistSampleStore(local, global_counts=[4, 4])
    # stand up the peer server exactly as rank 1 would (single-process
    # stores skip pre-pickling, so pack the served shard explicitly)
    peer = DistSampleStore(remote, global_counts=[4, 4])
    peer._local = [_pack_sample(s) for s in remote]
    peer._start_server()
    peer_addr = peer._server.getsockname()
    store._peers = [("127.0.0.1", 0), ("127.0.0.1", peer_addr[1])]
    store.rank = 0  # owner check: indices >= 4 are remote

    for gi in (4, 6, 7, 4):  # repeat 4 -> exercises the LRU cache
        got = store.get(gi)
        np.testing.assert_array_equal(got.x, remote[gi - 4].x)
        np.testing.assert_array_equal(got.edge_index, remote[gi - 4].edge_index)
    assert len(store._cache) == 3
    # out-of-range remote index is rejected cleanly
    try:
        store._fetch_remote(1, 99)
        raised = False
    except IndexError:
        raised = True
    assert raised
    store.close()
    peer.close()
