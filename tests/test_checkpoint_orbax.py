"""Orbax sharded-checkpoint backend: round-trip of a ZeRO-1-sharded
TrainState on the 8-device mesh, restored onto matching shardings."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from hydragnn_tpu.flagship import build_flagship
from hydragnn_tpu.parallel import make_mesh, place_state
from hydragnn_tpu.train import create_train_state, select_optimizer
from hydragnn_tpu.utils.checkpoint import load_existing_model, save_model


def _leaves_equal(a, b):
    fa = jax.tree_util.tree_leaves(a)
    fb = jax.tree_util.tree_leaves(b)
    assert len(fa) == len(fb)
    for x, y in zip(fa, fb):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def pytest_orbax_roundtrip_sharded_state(tmp_path):
    config, model, variables, loader = build_flagship(
        n_samples=16, hidden_dim=8, num_conv_layers=1, batch_size=4
    )
    tx = select_optimizer(config["NeuralNetwork"]["Training"])
    mesh = make_mesh(8)
    state = place_state(mesh, create_train_state(variables, tx), zero1=True)

    save_model(state, "orbax_rt", str(tmp_path), backend="orbax")

    # fresh target with the same shardings
    target = place_state(mesh, create_train_state(variables, tx, seed=1), zero1=True)
    # perturb so a no-op restore would be caught
    target = target.replace(
        params=jax.tree_util.tree_map(lambda x: x * 0 + 7.0, target.params)
    )
    restored = load_existing_model(target, "orbax_rt", str(tmp_path))
    _leaves_equal(restored.params, state.params)
    _leaves_equal(restored.opt_state, state.opt_state)
    # restored leaves keep their shardings (ZeRO-1 layout intact)
    for got, want in zip(
        jax.tree_util.tree_leaves(restored.opt_state),
        jax.tree_util.tree_leaves(state.opt_state),
    ):
        if hasattr(want, "sharding"):
            assert got.sharding.is_equivalent_to(want.sharding, got.ndim)


def pytest_msgpack_still_default_single_process(tmp_path):
    config, model, variables, loader = build_flagship(
        n_samples=16, hidden_dim=8, num_conv_layers=1, batch_size=4
    )
    tx = select_optimizer(config["NeuralNetwork"]["Training"])
    state = create_train_state(variables, tx)
    p = save_model(state, "mp_rt", str(tmp_path))
    assert p.endswith(".mp")
    restored = load_existing_model(
        create_train_state(variables, tx, seed=3), "mp_rt", str(tmp_path)
    )
    _leaves_equal(restored.params, state.params)


def pytest_msgpack_restore_preserves_shardings(tmp_path):
    """A msgpack checkpoint restored onto a placed (ZeRO-1) target keeps
    the target's shardings (the api resume ordering: place then load)."""
    config, model, variables, loader = build_flagship(
        n_samples=16, hidden_dim=8, num_conv_layers=1, batch_size=4
    )
    tx = select_optimizer(config["NeuralNetwork"]["Training"])
    state = create_train_state(variables, tx)
    save_model(state, "mp_shard_rt", str(tmp_path), backend="msgpack")

    mesh = make_mesh(8)
    target = place_state(mesh, create_train_state(variables, tx, seed=5), zero1=True)
    restored = load_existing_model(target, "mp_shard_rt", str(tmp_path))
    _leaves_equal(restored.params, state.params)
    for got, want in zip(
        jax.tree_util.tree_leaves(restored.opt_state),
        jax.tree_util.tree_leaves(target.opt_state),
    ):
        if hasattr(want, "sharding") and hasattr(got, "sharding"):
            assert got.sharding.is_equivalent_to(want.sharding, got.ndim)
