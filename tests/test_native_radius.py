"""Native C++ cell-list radius kernel vs the numpy fallback: identical
edge sets on the same inputs (plain and PBC paths, reference semantics:
hydragnn/preprocess/utils.py:99-171)."""

import numpy as np
import pytest

import importlib

rg = importlib.import_module("hydragnn_tpu.data.radius_graph")
from hydragnn_tpu.native import native_radius_pairs


@pytest.fixture
def big_cloud():
    rng = np.random.default_rng(3)
    # big enough to clear the brute-force cutoff in _candidate_pairs
    return rng.uniform(0, 12.0, (400, 3)).astype(np.float64)


def _edges_set(ei):
    return set(zip(ei[0].tolist(), ei[1].tolist()))


def pytest_native_available():
    assert native_radius_pairs(np.zeros((5, 3)), np.zeros((5, 3)), 0.1) is not None, (
        "native radius kernel failed to build/load"
    )


def pytest_native_matches_numpy_fallback(big_cloud, monkeypatch):
    ei_native = rg.radius_graph(big_cloud, 1.7)
    monkeypatch.setattr("hydragnn_tpu.native.native_radius_pairs", lambda *a: None)
    ei_numpy = rg.radius_graph(big_cloud, 1.7)
    assert _edges_set(ei_native) == _edges_set(ei_numpy)
    assert ei_native.shape == ei_numpy.shape


def pytest_native_matches_numpy_pbc(big_cloud, monkeypatch):
    cell = np.eye(3) * 12.0
    ei_native = rg.radius_graph_pbc(big_cloud, 1.7, cell)
    monkeypatch.setattr("hydragnn_tpu.native.native_radius_pairs", lambda *a: None)
    ei_numpy = rg.radius_graph_pbc(big_cloud, 1.7, cell)
    assert _edges_set(ei_native) == _edges_set(ei_numpy)


def pytest_native_matches_bruteforce():
    rng = np.random.default_rng(11)
    pos = rng.uniform(0, 8.0, (300, 3))
    r = 1.4
    diff = pos[:, None] - pos[None, :]
    dist = np.sqrt((diff**2).sum(-1))
    want = {(s, t) for s, t in zip(*np.nonzero(dist <= r)) if s != t}
    s, t, d = native_radius_pairs(pos, pos, r)
    got = {(int(a), int(b)) for a, b in zip(s, t) if a != b}
    assert got == want
    np.testing.assert_allclose(
        d, np.linalg.norm(pos[s] - pos[t], axis=1), rtol=1e-12
    )


def pytest_max_neighbors_cap(big_cloud):
    ei = rg.radius_graph(big_cloud, 2.5, max_num_neighbors=4)
    _, counts = np.unique(ei[1], return_counts=True)
    assert counts.max() <= 4


def pytest_native_outlier_falls_back():
    """A far outlier must not blow up the dense grid (returns None ->
    numpy fallback handles it), and the public API must stay correct."""
    rng = np.random.default_rng(2)
    pos = rng.uniform(0, 12.0, (400, 3))
    pos[0] = [2e5, 2e5, 2e5]
    assert native_radius_pairs(pos, pos, 1.7) is None
    ei = rg.radius_graph(pos, 1.7)  # falls back internally
    assert ei.shape[0] == 2 and (ei[0] != 0).all()  # outlier has no edges
