"""Round-trip test for the reference sharded-pickle importer.

Builds a fixture in the EXACT layout SimplePickleWriter emits
(reference: hydragnn/utils/pickledataset.py:74-146): <label>-meta.pkl
with 5 sequential pickles + one pickle per sample — each sample a
torch_geometric-style ``Data`` whose pickle bytes carry the real
``torch_geometric.data.data`` module path (faked via sys.modules, since
torch_geometric is deliberately not a dependency here), tensors packed
with the reference's y/y_loc head table
(serialized_dataset_loader.py:262-303)."""

import io
import os
import pickle
import sys
import types

import numpy as np
import pytest

torch = pytest.importorskip("torch")

from hydragnn_tpu.data.container import ContainerDataset
from hydragnn_tpu.data.import_reference import (
    ReferencePickleReader,
    import_pickle_dataset,
)


def _install_fake_pyg():
    """Register minimal torch_geometric.data.data / .storage modules so
    pickles carry the genuine PyG class paths."""
    if "torch_geometric" in sys.modules:
        return
    tg = types.ModuleType("torch_geometric")
    tg_data = types.ModuleType("torch_geometric.data")
    tg_data_data = types.ModuleType("torch_geometric.data.data")
    tg_storage = types.ModuleType("torch_geometric.data.storage")

    class GlobalStorage:
        def __init__(self, mapping):
            self._mapping = dict(mapping)

        # mirror BaseStorage pickling: plain __dict__ state
        def __getstate__(self):
            return {"_mapping": self._mapping}

        def __setstate__(self, state):
            self.__dict__.update(state)

    class Data:
        def __init__(self, **kwargs):
            self._store = GlobalStorage(kwargs)

        def __getstate__(self):
            return {"_store": self._store}

        def __setstate__(self, state):
            self.__dict__.update(state)

    GlobalStorage.__module__ = "torch_geometric.data.storage"
    GlobalStorage.__qualname__ = "GlobalStorage"
    Data.__module__ = "torch_geometric.data.data"
    Data.__qualname__ = "Data"
    tg_data_data.Data = Data
    tg_storage.GlobalStorage = GlobalStorage
    tg.data = tg_data
    tg_data.data = tg_data_data
    tg_data.storage = tg_storage
    sys.modules["torch_geometric"] = tg
    sys.modules["torch_geometric.data"] = tg_data
    sys.modules["torch_geometric.data.data"] = tg_data_data
    sys.modules["torch_geometric.data.storage"] = tg_storage
    return Data


def _write_fixture(basedir, label, n_samples, use_subdir=False, nmax_persubdir=2):
    Data = _install_fake_pyg() or sys.modules["torch_geometric.data.data"].Data
    rng = np.random.default_rng(7)
    os.makedirs(basedir, exist_ok=True)
    truth = []
    for k in range(n_samples):
        n = int(rng.integers(3, 7))
        x = rng.standard_normal((n, 3)).astype(np.float32)
        pos = rng.standard_normal((n, 3)).astype(np.float32)
        # ring graph, receiver-major enough for determinism
        send = np.arange(n, dtype=np.int64)
        recv = (send + 1) % n
        ei = np.stack([send, recv])
        # reference packed y: one graph head (dim 1) + one node head (dim 1)
        g_y = rng.standard_normal(1).astype(np.float32)
        n_y = rng.standard_normal((n, 1)).astype(np.float32)
        y = np.concatenate([g_y, n_y.reshape(-1)])[:, None]
        y_loc = np.array([[0, 1, 1 + n]], dtype=np.int64)
        d = Data(
            x=torch.from_numpy(x),
            pos=torch.from_numpy(pos),
            edge_index=torch.from_numpy(ei),
            y=torch.from_numpy(y),
            y_loc=torch.from_numpy(y_loc),
        )
        fname = f"{label}-{k}.pkl"
        if use_subdir:
            sub = os.path.join(basedir, str(k // nmax_persubdir))
            os.makedirs(sub, exist_ok=True)
            path = os.path.join(sub, fname)
        else:
            path = os.path.join(basedir, fname)
        with open(path, "wb") as f:
            pickle.dump(d, f)
        truth.append((x, pos, ei, g_y, n_y))
    minmax_node = torch.from_numpy(rng.standard_normal((2, 3)).astype(np.float32))
    with open(os.path.join(basedir, f"{label}-meta.pkl"), "wb") as f:
        pickle.dump(minmax_node, f)
        pickle.dump(None, f)
        pickle.dump(n_samples, f)
        pickle.dump(use_subdir, f)
        pickle.dump(nmax_persubdir, f)
    return truth


@pytest.mark.parametrize("use_subdir", [False, True])
def test_reader_matches_fixture(tmp_path, use_subdir):
    basedir = str(tmp_path / "pkl")
    truth = _write_fixture(basedir, "trainset", 5, use_subdir=use_subdir)
    # drop the fake modules: the reader must not need them
    for m in list(sys.modules):
        if m.startswith("torch_geometric"):
            del sys.modules[m]
    reader = ReferencePickleReader(basedir, "trainset")
    assert len(reader) == 5
    samples = reader.samples(head_types=["graph", "node"], head_names=["energy", "charge"])
    for s, (x, pos, ei, g_y, n_y) in zip(samples, truth):
        np.testing.assert_allclose(s.x, x, rtol=1e-6)
        np.testing.assert_allclose(s.pos, pos, rtol=1e-6)
        np.testing.assert_array_equal(s.edge_index, ei)
        np.testing.assert_allclose(s.graph_targets["energy"], g_y, rtol=1e-6)
        np.testing.assert_allclose(s.node_targets["charge"], n_y, rtol=1e-6)


def test_import_roundtrip_to_container(tmp_path):
    basedir = str(tmp_path / "pkl")
    out = str(tmp_path / "imported.hgc")
    truth = _write_fixture(basedir, "total", 4)
    for m in list(sys.modules):
        if m.startswith("torch_geometric"):
            del sys.modules[m]
    n = import_pickle_dataset(
        basedir, "total", out, head_types=["graph", "node"],
        head_names=["energy", "charge"],
    )
    assert n == 4
    ds = ContainerDataset(out)
    assert len(ds) == 4
    for i, (x, pos, ei, g_y, n_y) in enumerate(truth):
        s = ds.get(i)
        np.testing.assert_allclose(s.x, x, rtol=1e-6)
        np.testing.assert_array_equal(s.edge_index, ei)
        np.testing.assert_allclose(s.graph_targets["energy"], g_y, rtol=1e-6)
        np.testing.assert_allclose(s.node_targets["charge"], n_y, rtol=1e-6)
    ds.close()


def _write_monolithic(path, n_samples, rng_seed=9):
    """Mirror SerializedWriter (reference serializeddataset.py:49-87):
    3 sequential pickles — minmax_node, minmax_graph, list of Data."""
    Data = _install_fake_pyg() or sys.modules["torch_geometric.data.data"].Data
    rng = np.random.default_rng(rng_seed)
    objs, truth = [], []
    for _ in range(n_samples):
        n = int(rng.integers(3, 6))
        x = rng.standard_normal((n, 2)).astype(np.float32)
        send = np.arange(n, dtype=np.int64)
        ei = np.stack([send, (send + 1) % n])
        g_y = rng.standard_normal(1).astype(np.float32)
        y = g_y[:, None]
        objs.append(
            Data(
                x=torch.from_numpy(x),
                edge_index=torch.from_numpy(ei),
                y=torch.from_numpy(y),
            )
        )
        truth.append((x, ei, g_y))
    with open(path, "wb") as f:
        pickle.dump(torch.zeros(2, 2), f)
        pickle.dump(None, f)
        pickle.dump(objs, f)
    return truth


def test_monolithic_serialized_roundtrip(tmp_path):
    """SerializedDataset single-file and rank-sharded layouts convert
    through the CLI (reference: serializeddataset.py:30-36 naming)."""
    from hydragnn_tpu.data.import_reference import (
        ReferenceMonolithicReader,
        main,
    )

    single = str(tmp_path / "unit-total.pkl")
    truth = _write_monolithic(single, 4)
    # rank-sharded variant: base name has no file, only -0/-1 shards
    t0 = _write_monolithic(str(tmp_path / "dist-total-0.pkl"), 2, rng_seed=1)
    t1 = _write_monolithic(str(tmp_path / "dist-total-1.pkl"), 3, rng_seed=2)
    for m in list(sys.modules):
        if m.startswith("torch_geometric"):
            del sys.modules[m]

    out = str(tmp_path / "mono.hgc")
    main([single, out])
    ds = ContainerDataset(out)
    assert len(ds) == 4
    for i, (x, ei, g_y) in enumerate(truth):
        s = ds.get(i)
        np.testing.assert_allclose(s.x, x, rtol=1e-6)
        np.testing.assert_array_equal(s.edge_index, ei)
        # no y_loc in the legacy layout: y rides as the graph target
        np.testing.assert_allclose(np.ravel(s.graph_y), g_y, rtol=1e-6)
    ds.close()

    sharded = ReferenceMonolithicReader(str(tmp_path / "dist-total.pkl"))
    assert len(sharded) == 5
    got = sharded.samples()
    for s, (x, ei, g_y) in zip(got, t0 + t1):
        np.testing.assert_allclose(s.x, x, rtol=1e-6)
        np.testing.assert_allclose(np.ravel(s.graph_y), g_y, rtol=1e-6)


def test_malicious_globals_are_stubbed(tmp_path):
    """A pickle that REDUCEs through builtins.eval (or any global off
    the exact allowlist) must resolve to a harmless stub, never
    execute."""
    from hydragnn_tpu.data.import_reference import _Stub, _TolerantUnpickler

    canary = str(tmp_path / "pwned")

    class Evil:
        def __reduce__(self):
            return (eval, (f"open({canary!r}, 'w').close()",))

    obj = _TolerantUnpickler(io.BytesIO(pickle.dumps(Evil()))).load()
    assert isinstance(obj, _Stub)
    assert not os.path.exists(canary)

    # a whole-module torch path off the exact allowlist is stubbed too
    class EvilTorch:
        def __reduce__(self):
            import torch.serialization

            return (torch.serialization.load, (canary,))

    obj2 = _TolerantUnpickler(io.BytesIO(pickle.dumps(EvilTorch()))).load()
    assert isinstance(obj2, _Stub)


def test_head_type_inference_ambiguity_raises(tmp_path):
    """A head whose length divides num_nodes is AMBIGUOUS (a graph head
    of that size would be silently misclassified and its targets
    reshaped = corrupted), so inference refuses with a ValueError naming
    the head_types/--head-type escape hatch; heads shorter than
    num_nodes stay unambiguously graph-level and still infer."""
    basedir = str(tmp_path / "pkl")
    _write_fixture(basedir, "t", 2)
    for m in list(sys.modules):
        if m.startswith("torch_geometric"):
            del sys.modules[m]
    reader = ReferencePickleReader(basedir, "t")
    # head 1 (the node head, length == num_nodes) trips the ambiguity
    with pytest.raises(ValueError, match="head_types"):
        reader.read(0)
    # explicit types resolve it
    s = reader.read(0, head_types=["graph", "node"])
    assert len(s.graph_targets) == 1 and len(s.node_targets) == 1
    node_heads = list(s.node_targets.values())
    assert node_heads[0].shape[0] == s.num_nodes


def _write_coincident_fixture(basedir, label, n_samples, n_nodes=4):
    """Every sample has exactly ``n_nodes`` nodes and TWO heads of the
    SAME packed length ``n_nodes``: head 0 a graph-level vector of dim
    n_nodes, head 1 a per-node scalar — indistinguishable by size, the
    exact case the inference guard exists for."""
    Data = _install_fake_pyg() or sys.modules["torch_geometric.data.data"].Data
    rng = np.random.default_rng(11)
    os.makedirs(basedir, exist_ok=True)
    truth = []
    for k in range(n_samples):
        x = rng.standard_normal((n_nodes, 3)).astype(np.float32)
        send = np.arange(n_nodes, dtype=np.int64)
        ei = np.stack([send, (send + 1) % n_nodes])
        g_y = rng.standard_normal(n_nodes).astype(np.float32)
        n_y = rng.standard_normal((n_nodes, 1)).astype(np.float32)
        y = np.concatenate([g_y, n_y.reshape(-1)])[:, None]
        y_loc = np.array([[0, n_nodes, 2 * n_nodes]], dtype=np.int64)
        d = Data(
            x=torch.from_numpy(x),
            edge_index=torch.from_numpy(ei),
            y=torch.from_numpy(y),
            y_loc=torch.from_numpy(y_loc),
        )
        with open(os.path.join(basedir, f"{label}-{k}.pkl"), "wb") as f:
            pickle.dump(d, f)
        truth.append((x, g_y, n_y))
    with open(os.path.join(basedir, f"{label}-meta.pkl"), "wb") as f:
        for obj in (None, None, n_samples, False, 2):
            pickle.dump(obj, f)
    return truth


def test_multihead_coincident_sizes_need_explicit_types(tmp_path):
    """Mixed graph+node heads of COINCIDENT packed size: refuse without
    explicit types; with head_types each head lands in the right target
    dict with the right shape, through the full container round-trip."""
    basedir = str(tmp_path / "pkl")
    out = str(tmp_path / "coincident.hgc")
    truth = _write_coincident_fixture(basedir, "total", 3, n_nodes=4)
    for m in list(sys.modules):
        if m.startswith("torch_geometric"):
            del sys.modules[m]

    reader = ReferencePickleReader(basedir, "total")
    with pytest.raises(ValueError, match="--head-type"):
        reader.samples()

    n = import_pickle_dataset(
        basedir,
        "total",
        out,
        head_types=["graph", "node"],
        head_names=["spectrum", "charge"],
    )
    assert n == 3
    ds = ContainerDataset(out)
    for i, (x, g_y, n_y) in enumerate(truth):
        s = ds.get(i)
        np.testing.assert_allclose(s.x, x, rtol=1e-6)
        # the graph head keeps its 4-dim vector form (NOT reshaped to
        # per-node); the node head is [num_nodes, 1]
        np.testing.assert_allclose(
            np.ravel(s.graph_targets["spectrum"]), g_y, rtol=1e-6
        )
        assert s.node_targets["charge"].shape == (4, 1)
        np.testing.assert_allclose(s.node_targets["charge"], n_y, rtol=1e-6)
    ds.close()
