"""Subprocess smoke tests for the example drivers (reference:
tests/test_examples.py:18-26 runs qm9 and md17 the same way). Each
example runs offline on its synthetic fallback dataset with tiny sizes.
"""

import os
import subprocess
import sys

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_example(subdir: str, script: str, *args: str) -> None:
    path = os.path.join(_REPO, "examples", subdir)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("XLA_FLAGS", None)  # single-device run is enough for a smoke test
    ret = subprocess.run(
        [sys.executable, script, *args],
        cwd=path,
        env=env,
        capture_output=True,
        text=True,
        timeout=600,
    )
    assert ret.returncode == 0, f"{subdir}/{script} failed:\n{ret.stdout}\n{ret.stderr}"


@pytest.mark.parametrize(
    "subdir,script,args",
    [
        ("qm9", "qm9.py", ["--nsamples", "120"]),
        ("md17", "md17.py", ["--maxframes", "150"]),
    ],
)
def pytest_examples_train(subdir, script, args):
    _run_example(subdir, script, *args)


@pytest.mark.parametrize(
    "subdir,script,args",
    [
        ("ising_model", "train_ising.py", ["--natom", "2", "--cutoff", "6"]),
        ("lsms", "lsms.py", ["--nconfig", "40"]),
        ("eam", "eam.py", ["--nconfig", "30"]),
        ("ogb", "train_gap.py", ["--sampling", "0.05"]),
        ("csce", "train_gap.py", ["--sampling", "0.2"]),
    ],
)
def pytest_example_preonly_then_train(subdir, script, args):
    """Container (--preonly) pipelines of the scalable-data examples end
    to end on their synthetic fallbacks, incl. heavy sampling that must
    not empty a split (reference pipeline shape:
    examples/ogb/train_gap.py:238-378)."""
    import shutil

    # drivers skip synthetic generation when raw data already exists;
    # clear it so the tiny test sizes actually take effect
    shutil.rmtree(os.path.join(_REPO, "examples", subdir, "dataset"),
                  ignore_errors=True)
    _run_example(subdir, script, "--preonly", *args)
    _run_example(subdir, script, *args)
