"""Subprocess smoke tests for the example drivers (reference:
tests/test_examples.py:18-26 runs qm9 and md17 the same way). Each
example runs offline on its synthetic fallback dataset with tiny sizes.
"""

import os
import shutil
import subprocess
import sys

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _example_copy(subdir: str, tmp_path) -> str:
    """Hermetic working copy of the example dir: runs never touch (or
    depend on) datasets/artifacts in the repo tree — a developer's real
    downloaded data under examples/<subdir>/dataset stays untouched and
    the tiny test sizes always take effect."""
    dst = os.path.join(str(tmp_path), subdir)
    if not os.path.isdir(dst):
        shutil.copytree(
            os.path.join(_REPO, "examples", subdir),
            dst,
            ignore=shutil.ignore_patterns("dataset", "logs", "__pycache__"),
        )
    return dst


def _run_example(subdir: str, script: str, *args: str, workdir: str = None) -> None:
    path = workdir or os.path.join(_REPO, "examples", subdir)
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=_REPO)
    env.pop("XLA_FLAGS", None)  # single-device run is enough for a smoke test
    ret = subprocess.run(
        [sys.executable, script, *args],
        cwd=path,
        env=env,
        capture_output=True,
        text=True,
        timeout=600,
    )
    assert ret.returncode == 0, f"{subdir}/{script} failed:\n{ret.stdout}\n{ret.stderr}"


@pytest.mark.parametrize(
    "subdir,script,args",
    [
        ("qm9", "qm9.py", ["--nsamples", "120"]),
        ("md17", "md17.py", ["--maxframes", "150"]),
    ],
)
def pytest_examples_train(subdir, script, args, tmp_path):
    _run_example(subdir, script, *args, workdir=_example_copy(subdir, tmp_path))


@pytest.mark.parametrize(
    "subdir,script,args",
    [
        ("ising_model", "train_ising.py", ["--natom", "2", "--cutoff", "6"]),
        ("lsms", "lsms.py", ["--nconfig", "40"]),
        ("eam", "eam.py", ["--nconfig", "30"]),
        ("ogb", "train_gap.py", ["--sampling", "0.05"]),
        ("csce", "train_gap.py", ["--sampling", "0.2"]),
    ],
)
def pytest_example_preonly_then_train(subdir, script, args, tmp_path):
    """Container (--preonly) pipelines of the scalable-data examples end
    to end on their synthetic fallbacks, incl. heavy sampling that must
    not empty a split (reference pipeline shape:
    examples/ogb/train_gap.py:238-378). Both phases share one hermetic
    working copy (preonly writes the containers the train run reads)."""
    workdir = _example_copy(subdir, tmp_path)
    _run_example(subdir, script, "--preonly", *args, workdir=workdir)
    _run_example(subdir, script, *args, workdir=workdir)
