"""Unified-telemetry tests (hydragnn_tpu/obs): registry semantics,
flight-record schema round-trip, compile-monitor windows (including the
acceptance contract — zero train-step recompiles after step 1), span
tracing, exporters, the disabled path's zero-overhead guarantees, the
bench retry-with-backoff, and the chip-hygiene report."""

import io
import json
import os
import threading

import numpy as np
import pytest

from hydragnn_tpu.obs import (
    BACKEND_COMPILE_EVENT,
    CompileMonitor,
    FlightRecorder,
    MetricsRegistry,
    StepSpans,
    get_registry,
    read_flight_record,
    registry_to_jsonl,
    registry_to_prometheus,
    registry_to_prometheus_text,
    reset_registry,
    validate_flight_record,
)
from hydragnn_tpu.obs.registry import NULL_COUNTER, NULL_GAUGE, NULL_HISTOGRAM


# ---------------------------------------------------------------------------
# metrics registry
# ---------------------------------------------------------------------------


def test_registry_counter_gauge_histogram_semantics():
    r = MetricsRegistry(rank=0)
    c = r.counter("train.steps")
    c.inc()
    c.inc(3)
    assert c.value == 4
    assert r.counter("train.steps") is c  # same name -> same metric

    g = r.gauge("serve.queue_depth")
    g.set(7)
    g.set(2)
    assert g.value == 2 and g.peak == 7

    h = r.histogram("latency_s", window=4)
    for v in (0.1, 0.2, 0.3, 0.4, 0.5):
        h.observe(v)
    snap = h.snapshot()
    # window=4: the 0.1 aged out of percentiles, but count/sum are all-time
    assert snap["count"] == 5 and abs(snap["sum"] - 1.5) < 1e-9
    assert snap["p50"] == pytest.approx(0.4) and snap["p99"] == pytest.approx(0.5)

    nested = r.snapshot()
    assert nested["train"]["steps"] == 4
    assert nested["serve"]["queue_depth"] == 2
    assert nested["latency_s"]["count"] == 5

    with pytest.raises(TypeError):
        r.gauge("train.steps")  # name already registered as a Counter


def test_registry_thread_safety_smoke():
    r = MetricsRegistry()
    c = r.counter("hits")

    def worker():
        for _ in range(1000):
            c.inc()

    threads = [threading.Thread(target=worker) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert c.value == 4000


def test_disabled_registry_hands_out_null_singletons():
    r = MetricsRegistry(enabled=False)
    c, g, h = r.counter("a"), r.gauge("b"), r.histogram("c")
    # process-wide singletons: the disabled path allocates no metric
    # objects per call site, and recording is a no-op
    assert c is NULL_COUNTER and g is NULL_GAUGE and h is NULL_HISTOGRAM
    c.inc(100)
    g.set(5)
    h.observe(1.0)
    assert c.value == 0 and g.value == 0 and h.count == 0
    assert r.snapshot() == {}


def test_global_registry_honors_env_gate(monkeypatch):
    monkeypatch.setenv("HYDRAGNN_TELEMETRY", "0")
    reset_registry()
    try:
        assert get_registry().enabled is False
        assert get_registry().counter("x") is NULL_COUNTER
    finally:
        monkeypatch.delenv("HYDRAGNN_TELEMETRY")
        reset_registry()


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------


def test_flight_record_roundtrip_and_schema(tmp_path):
    path = str(tmp_path / "flight.jsonl")
    with FlightRecorder(path) as fr:
        fr.start_run({"run": "t", "config": {"a": 1}, "pad_plans": {}})
        fr.epoch(
            0,
            train_loss=1.0,
            val_loss=2.0,
            step_time={"data_wait_s": 0.1, "dispatch_s": 0.2},
            compiles={"count": 3, "available": True},
        )
        fr.retry(1, "UNAVAILABLE: chip busy", stage="backend_init")
        fr.error(ValueError("boom"), stage="epoch")
        fr.end_run(status="completed", epochs=1)
    events = read_flight_record(path)
    assert [e["kind"] for e in events] == [
        "run_start",
        "epoch",
        "retry",
        "error",
        "run_end",
    ]
    # envelope + autofilled manifest environment fields
    man = events[0]["manifest"]
    assert man["jax_version"] and man["backend"] and man["num_processes"] >= 1
    assert all({"v", "kind", "t", "rank"} <= set(e) for e in events)
    assert events[3]["error_type"] == "ValueError"
    assert validate_flight_record(path, require_complete=True) == []


def test_flight_record_tolerates_truncated_tail(tmp_path):
    path = str(tmp_path / "flight.jsonl")
    with FlightRecorder(path) as fr:
        fr.start_run({"run": "t"})
        fr.epoch(0, train_loss=1.0, val_loss=1.0)
    with open(path, "a") as f:
        f.write('{"v": 1, "kind": "run_end", "t": 1.0, "ra')  # crash mid-write
    events = read_flight_record(path)
    assert [e["kind"] for e in events] == ["run_start", "epoch"]
    # incomplete run still validates structurally...
    assert validate_flight_record(events) == []
    # ...but fails the completeness gate (no run_end)
    problems = validate_flight_record(events, require_complete=True)
    assert any("run_end" in p for p in problems)


def test_flight_record_validation_flags_missing_fields(tmp_path):
    path = str(tmp_path / "bad.jsonl")
    with open(path, "w") as f:
        f.write(json.dumps({"v": 1, "kind": "epoch", "t": 1.0, "rank": 0}) + "\n")
        f.write("not json at all\n")
        f.write(json.dumps({"v": 1, "kind": "run_end", "t": 2.0, "rank": 0, "status": "x"}) + "\n")
    problems = validate_flight_record(path)
    assert any("train_loss" in p for p in problems)
    assert any("unparseable" in p for p in problems)


def test_disabled_flight_recorder_writes_nothing(tmp_path):
    path = str(tmp_path / "off.jsonl")
    fr = FlightRecorder(path, enabled=False)
    fr.start_run({"run": "t"})
    fr.end_run(status="completed")
    fr.close()
    assert not os.path.exists(path)
    # a None path is equally inert (the server's default)
    FlightRecorder(None).record("anything")


# ---------------------------------------------------------------------------
# span tracing
# ---------------------------------------------------------------------------


def test_spans_decompose_data_wait_dispatch_device():
    import jax.numpy as jnp

    spans = StepSpans(sample_steps=2, skip_first=1)
    spans.epoch_start(0)

    def slow_loader():
        import time

        for _ in range(4):
            time.sleep(0.002)
            yield jnp.ones(())

    def step(x):
        return x + 1

    for batch in spans.timed_iter(slow_loader()):
        spans.step(step, batch)
    snap = spans.epoch_snapshot()
    assert snap["steps"] == 4
    assert snap["data_wait_s"] >= 0.004  # the loader sleeps were seen
    assert snap["dispatch_s"] > 0
    assert snap["sampled_steps"] == 2  # steps 1 and 2 were fenced
    assert snap["device_wait_ms_mean"] is not None
    assert snap["sync_step_ms_mean"] >= 0
    # epoch reset
    spans.epoch_start(1)
    assert spans.epoch_snapshot()["steps"] == 0


def test_disabled_spans_add_no_per_step_work(monkeypatch):
    """The telemetry-off contract: identity iteration, direct step
    calls, and NO device syncs — block_until_ready is poisoned to prove
    the disabled path never touches it."""
    import jax

    def _boom(*a, **kw):  # pragma: no cover - must never run
        raise AssertionError("disabled spans must not sync")

    monkeypatch.setattr(jax, "block_until_ready", _boom)
    spans = StepSpans.disabled()
    spans.epoch_start(0)
    batches = [1, 2, 3]
    assert spans.timed_iter(batches) is batches  # identity, not a wrapper
    calls = []
    out = spans.step(lambda x: calls.append(x) or x * 2, 21)
    assert out == 42 and calls == [21]
    assert spans.epoch_snapshot() is None
    # disabled() returns the shared singleton: no per-epoch allocation
    assert StepSpans.disabled() is StepSpans.disabled()


# ---------------------------------------------------------------------------
# compile monitor
# ---------------------------------------------------------------------------


def test_compile_monitor_counts_backend_compiles():
    import jax
    import jax.numpy as jnp

    # arrays built OUTSIDE the monitored windows: jnp.ones itself
    # dispatches a fill computation whose compile would otherwise be
    # (correctly!) counted against the window
    x3, x5 = jnp.ones((3,)), jnp.ones((5,))
    with CompileMonitor() as mon:
        assert mon.available, "jax.monitoring should exist on this jax"

        @jax.jit
        def f(x):
            return x * 2 + 1

        f(x3)  # compile
        assert mon.count >= 1
        mon.mark("warm")
        f(x3)  # cache hit
        f(x3)
        assert mon.count_since("warm") == 0
        f(x5)  # new shape -> recompile
        assert mon.count_since("warm") == 1
    snap = mon.snapshot()
    assert snap["count"] == mon.count and snap["total_duration_s"] >= 0


def test_monitor_stop_detaches_from_event_stream():
    import jax
    import jax.numpy as jnp

    mon = CompileMonitor().start()
    mon.stop()
    before = mon.count

    @jax.jit
    def g(x):
        return x - 1

    g(jnp.ones((7,)))
    assert mon.count == before  # events after stop() are not counted


@pytest.fixture(scope="module")
def tiny_flagship():
    from hydragnn_tpu.flagship import build_flagship

    config, model, variables, loader = build_flagship(
        n_samples=12,
        hidden_dim=8,
        num_conv_layers=2,
        batch_size=4,
        unit_cells=(2, 3),
    )
    return config, model, variables, loader


def test_zero_train_step_recompiles_after_step_one(tiny_flagship):
    """The acceptance contract: repeated same-shape train steps compile
    exactly once — every step after step 1 is a cache hit, measured by
    the jax.monitoring event stream, the same way serving proves its
    steady-state no-compile property."""
    from hydragnn_tpu.train import create_train_state, make_train_step, select_optimizer

    config, model, variables, loader = tiny_flagship
    tx = select_optimizer(config["NeuralNetwork"]["Training"])
    state = create_train_state(variables, tx)
    step = make_train_step(model, tx)
    batches = list(loader)
    assert len(batches) >= 2

    with CompileMonitor() as mon:
        state, loss, _ = step(state, batches[0])  # step 1: the one compile
        import jax

        jax.block_until_ready(loss)
        assert mon.count >= 1, "step 1 must have compiled"
        mon.mark("after_step_1")
        for i in range(4):
            state, loss, _ = step(state, batches[i % len(batches)])
        jax.block_until_ready(loss)
        assert mon.count_since("after_step_1") == 0, (
            "train step recompiled after step 1 — the fixed-shape loader "
            "contract is broken"
        )


# ---------------------------------------------------------------------------
# exporters
# ---------------------------------------------------------------------------


def _example_registry() -> MetricsRegistry:
    r = MetricsRegistry(rank=0)
    r.counter("serve.requests_total").inc(5)
    r.gauge("serve.queue_depth").set(3)
    h = r.histogram("serve.latency_s")
    h.observe(0.01)
    h.observe(0.03)
    return r


def test_prometheus_text_format():
    text = registry_to_prometheus_text(_example_registry())
    assert "# TYPE hydragnn_serve_requests_total counter" in text
    assert 'hydragnn_serve_requests_total{rank="0"} 5' in text
    assert "# TYPE hydragnn_serve_queue_depth gauge" in text
    assert 'hydragnn_serve_latency_s{rank="0",quantile="0.50"} 0.01' in text
    assert 'hydragnn_serve_latency_s_count{rank="0"} 2' in text


def test_prometheus_textfile_atomic_write(tmp_path):
    path = str(tmp_path / "metrics" / "hydragnn.prom")
    registry_to_prometheus(_example_registry(), path)
    with open(path) as f:
        assert "hydragnn_serve_requests_total" in f.read()
    assert not [p for p in os.listdir(os.path.dirname(path)) if ".tmp." in p]


def test_registry_jsonl_export(tmp_path):
    path = str(tmp_path / "metrics.jsonl")
    registry_to_jsonl(path, _example_registry(), extra={"phase": "test"})
    registry_to_jsonl(path, _example_registry())
    lines = [json.loads(line) for line in open(path)]
    assert len(lines) == 2
    assert lines[0]["phase"] == "test"
    assert lines[0]["metrics"]["serve"]["requests_total"] == 5


def test_tensorboard_export_handles_numpy_scalars():
    from hydragnn_tpu.utils.tensorboard import write_scalar_dict

    class _Rec:
        def __init__(self):
            self.rows = []

        def add_scalar(self, tag, value, step):
            self.rows.append((tag, value, step))

    w = _Rec()
    n = write_scalar_dict(
        w,
        {"a": np.float32(1.5), "b": {"c": np.int64(2), "skip": "str"}},
        step=3,
        prefix="obs",
    )
    assert n == 2
    assert ("obs/a", 1.5, 3) in w.rows and ("obs/b/c", 2.0, 3) in w.rows


def test_serve_metrics_is_registry_backed():
    from hydragnn_tpu.serve.metrics import ServeMetrics

    m = ServeMetrics(num_buckets=1)
    m.record_request(0)
    m.observe_latency(0.02)
    reg_snap = m.registry.snapshot()
    assert reg_snap["serve"]["requests_total"] == 1
    assert reg_snap["serve"]["bucket_0"]["requests"] == 1
    assert "hydragnn_serve_requests_total" in m.to_prometheus_text()
    # two servers' metrics never alias (private registries by default)
    m2 = ServeMetrics(num_buckets=1)
    assert m2.snapshot()["requests_total"] == 0


# ---------------------------------------------------------------------------
# backend-init retry with backoff (bench satellite)
# ---------------------------------------------------------------------------


def test_init_retry_recovers_from_transient_failures(monkeypatch):
    from hydragnn_tpu.utils import platform as plat

    attempts = {"n": 0}

    def flaky_pin():
        attempts["n"] += 1
        if attempts["n"] <= 2:
            raise RuntimeError("UNAVAILABLE: failed to connect to TPU worker")

    sleeps, retries_seen = [], []
    monkeypatch.setattr(plat, "pin_platform_from_env", flaky_pin)
    monkeypatch.setattr(plat, "_clear_failed_backends", lambda: None)
    devices, retries = plat.init_backend_with_retry(
        attempts=5,
        delays=(0.01, 0.02),
        sleep=sleeps.append,
        on_retry=lambda a, e, d: retries_seen.append(a),
    )
    assert retries == 2 and len(devices) >= 1
    assert sleeps == [0.01, 0.02]  # backoff schedule consumed in order
    assert retries_seen == [1, 2]


def test_init_retry_fails_fast_on_config_errors(monkeypatch):
    from hydragnn_tpu.utils import platform as plat

    calls = {"n": 0}

    def bad_pin():
        calls["n"] += 1
        raise RuntimeError("Unknown backend: 'axon9' requested")

    monkeypatch.setattr(plat, "pin_platform_from_env", bad_pin)
    monkeypatch.setattr(plat, "_clear_failed_backends", lambda: None)
    with pytest.raises(plat.BackendInitError) as ei:
        plat.init_backend_with_retry(attempts=5, delays=(0.01,), sleep=lambda s: None)
    assert calls["n"] == 1  # no retries burned on a genuine config error
    assert ei.value.record["retries"] == 0


def test_init_retry_exhaustion_reports_retry_count(monkeypatch):
    from hydragnn_tpu.utils import platform as plat

    def always_down():
        raise RuntimeError("UNAVAILABLE: chip busy")

    monkeypatch.setattr(plat, "pin_platform_from_env", always_down)
    monkeypatch.setattr(plat, "_clear_failed_backends", lambda: None)
    with pytest.raises(plat.BackendInitError) as ei:
        plat.init_backend_with_retry(attempts=3, delays=(0.0,), sleep=lambda s: None)
    assert ei.value.record["retries"] == 2  # 3 attempts = 2 retries
    assert "retries" in ei.value.record


def test_transient_classifier():
    from hydragnn_tpu.utils.platform import is_transient_backend_error

    assert is_transient_backend_error(RuntimeError("UNAVAILABLE: socket closed"))
    assert is_transient_backend_error(RuntimeError("Device or resource busy"))
    assert not is_transient_backend_error(RuntimeError("Unknown backend 'foo'"))


# ---------------------------------------------------------------------------
# chip hygiene report
# ---------------------------------------------------------------------------


def test_chip_hygiene_report_structure():
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir, "tools"))
    try:
        import chip_hygiene
    finally:
        sys.path.pop(0)

    report = chip_hygiene.find_chip_holders()
    assert {"targets_present", "holders", "foreign_holder_count", "unreadable_proc_count"} <= set(report)
    for h in report["holders"]:
        assert {"pid", "cmdline", "targets", "is_self_tree"} <= set(h)


def test_chip_hygiene_detects_self_held_lockfile(tmp_path, monkeypatch):
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir, "tools"))
    try:
        import chip_hygiene
    finally:
        sys.path.pop(0)

    lock = tmp_path / "libtpu_lockfile"
    lock.write_text("")
    monkeypatch.setattr(
        chip_hygiene, "_TARGET_GLOBS", (str(tmp_path / "libtpu_lockfile*"),)
    )
    with open(lock):
        report = chip_hygiene.find_chip_holders()
    me = [h for h in report["holders"] if h["pid"] == os.getpid()]
    assert me and me[0]["is_self_tree"]
    assert report["foreign_holder_count"] == 0  # our own tree is not "lingering"


# ---------------------------------------------------------------------------
# obs_report tool
# ---------------------------------------------------------------------------


def _write_run(path, run_name, losses, status="completed"):
    with FlightRecorder(str(path)) as fr:
        fr.start_run(
            {"run": run_name, "config": {"lr": 1e-3}, "num_epoch": len(losses)}
        )
        for ep, loss in enumerate(losses):
            fr.epoch(
                ep,
                train_loss=loss,
                val_loss=loss * 1.1,
                lr=1e-3,
                step_time={
                    "mode": "per_step",
                    "steps": 4,
                    "data_wait_s": 0.01,
                    "dispatch_s": 0.1,
                    "device_wait_ms_mean": 1.5,
                },
                compiles={"count": 9 if ep == 0 else 0, "available": True},
            )
        fr.end_run(status=status, epochs=len(losses), best_val_loss=min(losses) * 1.1)


def test_obs_report_render_and_validate(tmp_path, capsys):
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir, "tools"))
    try:
        import obs_report
    finally:
        sys.path.pop(0)

    a = tmp_path / "a.jsonl"
    _write_run(a, "run_a", [1.0, 0.5])
    events = read_flight_record(str(a))
    text = obs_report.render_report(events)
    assert "== manifest ==" in text and "run_a" in text
    assert "== epochs ==" in text and "data_wait_s" in text
    assert "== run_end ==" in text

    assert obs_report.main(["--validate", "--require-complete", str(a)]) == 0
    out = capsys.readouterr().out
    assert "OK" in out


def test_obs_report_diff(tmp_path):
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir, "tools"))
    try:
        import obs_report
    finally:
        sys.path.pop(0)

    a, b = tmp_path / "a.jsonl", tmp_path / "b.jsonl"
    _write_run(a, "run_a", [1.0, 0.5])
    _write_run(b, "run_b", [0.9, 0.4, 0.3])
    text = obs_report.render_diff(
        read_flight_record(str(a)), read_flight_record(str(b))
    )
    assert "manifest drift" in text
    assert "run: run_a -> run_b" in text
    assert "ep 0:" in text and "train_loss -0.1" in text
    assert "epochs only in B: [2]" in text


# ---------------------------------------------------------------------------
# acceptance: a default run_training emits a schema-valid flight record
# ---------------------------------------------------------------------------


def test_run_training_emits_valid_flight_record(tmp_path, monkeypatch):
    from hydragnn_tpu.api import run_training
    from hydragnn_tpu.data.synthetic import deterministic_graph_data
    from hydragnn_tpu.flagship import flagship_config

    # introspection is conftest-disabled for the suite's many tiny
    # trainings; THIS test asserts the production default-on record
    monkeypatch.setenv("HYDRAGNN_DIAGNOSTICS", "1")
    log_dir = str(tmp_path / "logs") + "/"
    cfg = flagship_config(hidden_dim=8, num_conv_layers=2, batch_size=5, num_epoch=2)
    samples = deterministic_graph_data(
        number_configurations=20,
        unit_cell_x_range=(2, 3),
        unit_cell_y_range=(2, 3),
        unit_cell_z_range=(2, 3),
        seed=0,
    )
    run_training(cfg, samples=samples, log_dir=log_dir)

    import glob

    paths = glob.glob(log_dir + "*/flight.jsonl")
    assert len(paths) == 1, "default run_training must write one flight record"
    assert validate_flight_record(paths[0], require_complete=True) == []
    events = read_flight_record(paths[0])
    man = [e for e in events if e["kind"] == "run_start"][0]["manifest"]
    # resolved config + environment + pad plans in the manifest
    assert "NeuralNetwork" in man["config"]
    assert man["backend"] and man["jax_version"]
    assert man["pad_plans"]["train"]["pad_nodes"] > 0
    assert man["mesh"]["process_count"] >= 1

    # v2 manifest: the introspection identity card
    assert man["head_names"] == ["sum_x_x2_x3", "x", "x2", "x3"]
    assert man["diagnostics"]["enabled"] is True
    assert "available" in man["hw_cost"]
    if man["hw_cost"]["available"]:
        assert man["hw_cost"]["flops_per_step"] > 0

    # dispatch-mode resolution: the single-device default is the
    # whole-epoch scan dispatch, recorded with its reason; the per-step
    # span decomposition is pinned by tests/test_dispatch_modes.py
    # (explicit Training.scan_epoch=false)
    dm = man["dispatch_mode"]
    assert dm["mode"] == "scan_epoch" and dm["auto"] is True, dm
    assert man["scan_epoch"] is True

    epochs = [e for e in events if e["kind"] == "epoch"]
    assert len(epochs) == 2
    for ep in epochs:
        st = ep["step_time"]
        assert st["mode"] == "scan_epoch"
        assert "count" in ep["compiles"] and ep["compiles"]["available"]
        # per-task losses keyed by head name, not positional index
        assert set(ep["train_tasks"]) == set(man["head_names"])
        assert set(ep["val_tasks"]) == set(man["head_names"])
        # model-level introspection: per-head grad norms, the conflict
        # matrix, per-head MAE/RMSE, and the hardware ledger
        heads = ep["heads"]
        assert heads["available"]
        assert set(heads["grad_norm"]) == set(man["head_names"])
        cos = heads["cosine"]
        assert len(cos) == 4 and all(len(row) == 4 for row in cos)
        assert all(abs(cos[i][i] - 1.0) < 1e-5 for i in range(4))
        assert set(heads["mae"]) == set(man["head_names"])
        assert set(heads["rmse"]) == set(man["head_names"])
        hw = ep["hw"]
        assert "available" in hw and "available" in hw["memory"]
        if hw["available"]:
            assert hw["achieved_tflops"] > 0 and "mfu" in hw
    # steady state: epoch 1 must not have recompiled the train step —
    # including the separate diagnostics executable (compiled in epoch
    # 0, cache-hit thereafter)
    assert epochs[1]["compiles"]["unexpected"] is False
    assert epochs[1]["compiles"]["count"] == 0

    end = events[-1]
    assert end["kind"] == "run_end" and end["status"] == "completed"
    assert end["timers"] and "metrics" in end


def test_crashed_training_leaves_failed_flight_record(tmp_path):
    """A run that dies mid-epoch-loop must still leave a structurally
    valid flight record ending in a failed run_end with the error event
    — the r05 'only a traceback to explain it' failure mode, closed."""
    from hydragnn_tpu.api import prepare_loaders_and_config, train_with_loaders
    from hydragnn_tpu.data.synthetic import deterministic_graph_data
    from hydragnn_tpu.flagship import flagship_config

    cfg = flagship_config(hidden_dim=8, num_conv_layers=2, batch_size=5, num_epoch=3)
    # the crash simulation is iteration-based, so pin the per-step
    # dispatch (the auto default would scan stacked batches and never
    # touch __iter__ during the epoch loop)
    cfg["NeuralNetwork"]["Training"]["scan_epoch"] = False
    samples = deterministic_graph_data(
        number_configurations=20,
        unit_cell_x_range=(2, 3),
        unit_cell_y_range=(2, 3),
        unit_cell_z_range=(2, 3),
        seed=0,
    )
    tr, va, te, cfg = prepare_loaders_and_config(cfg, samples)

    class Boom:
        """Crashes on the THIRD iteration: 1 = model-init example,
        2 = epoch 0 training, 3 = epoch 1 -> a genuine mid-run crash.
        (With introspection enabled — HYDRAGNN_DIAGNOSTICS=1, off in
        this suite — the hardware ledger consumes one extra example
        iteration before epoch 0.)"""

        def __init__(self, inner):
            self.inner = inner
            self.n = 0

        def __getattr__(self, k):
            return getattr(self.inner, k)

        def __len__(self):
            return len(self.inner)

        def set_epoch(self, e):
            self.inner.set_epoch(e)

        def __iter__(self):
            self.n += 1
            if self.n >= 3:
                raise RuntimeError("synthetic mid-run crash")
            return iter(self.inner)

    log_dir = str(tmp_path / "logs") + "/"
    with pytest.raises(RuntimeError, match="synthetic mid-run crash"):
        train_with_loaders(cfg, Boom(tr), va, te, log_dir=log_dir)

    import glob

    paths = glob.glob(log_dir + "*/flight.jsonl")
    assert paths, "failed run must still leave a flight record"
    events = read_flight_record(paths[0])
    kinds = [e["kind"] for e in events]
    assert kinds[0] == "run_start" and kinds[-1] == "run_end"
    assert "error" in kinds and "epoch" in kinds  # epoch 0 completed
    assert events[-1]["status"] == "failed" and events[-1]["epochs"] == 1
    err = [e for e in events if e["kind"] == "error"][0]
    assert err["error_type"] == "RuntimeError"
    assert validate_flight_record(events) == []  # crashed, still parseable
    # the process-global epoch timer must not be left running — a leaked
    # interval poisons every later training run in this process
    from hydragnn_tpu.utils.time_utils import Timer

    assert Timer("train_validate_test")._start is None
