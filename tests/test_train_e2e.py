"""End-to-end train-to-accuracy tests on the deterministic dataset.

Port of the reference's primary coverage (reference:
tests/test_graphs.py:24-192): generate the synthetic BCC dataset with a
known closed-form target, run the full run_training/run_prediction
pipeline, and assert per-head RMSE and sample MAE under per-model
thresholds (reference threshold table: tests/test_graphs.py:126-139).

The fast default pass covers GIN (simplest conv) and PNA (the reference's
flagship, exercised single-head, multihead, and reloaded-from-checkpoint)
at reference thresholds, plus a 15-epoch relaxed-threshold smoke of the
other five flavors so training-dynamics regressions are caught by the
default suite; the full 7-model matrix at reference thresholds runs in
tests/test_train_matrix.py behind the HYDRAGNN_FULL_MATRIX env flag.
"""

import os

import numpy as np
import pytest

from hydragnn_tpu.api import run_prediction, run_training
from hydragnn_tpu.data.synthetic import deterministic_graph_data

# Reference accuracy thresholds (tests/test_graphs.py:126-139).
THRESHOLDS = {
    "PNA": [0.20, 0.20],
    "MFC": [0.20, 0.20],
    "GIN": [0.25, 0.20],
    "GAT": [0.60, 0.70],
    "CGCNN": [0.50, 0.40],
    "SAGE": [0.20, 0.20],
    "SchNet": [0.20, 0.20],
}


def make_config(model_type: str, multihead: bool, tmp_dir: str, num_epoch: int = 40):
    if multihead:
        voi = {
            "input_node_features": [0],
            "output_names": ["sum_x_x2_x3", "x", "x2", "x3"],
            "output_index": [0, 0, 1, 2],
            "type": ["graph", "node", "node", "node"],
        }
        task_weights = [4.0, 2.0, 2.0, 2.0]
    else:
        voi = {
            "input_node_features": [0],
            "output_names": ["sum_x_x2_x3"],
            "output_index": [0],
            "type": ["graph"],
        }
        task_weights = [1.0]
    arch = {
        "model_type": model_type,
        "radius": 2.0,
        "max_neighbours": 100,
        "periodic_boundary_conditions": False,
        "hidden_dim": 8,
        "num_conv_layers": 2,
        "output_heads": {
            "graph": {
                "num_sharedlayers": 2,
                "dim_sharedlayers": 5,
                "num_headlayers": 2,
                "dim_headlayers": [50, 25],
            },
            "node": {
                "num_headlayers": 2,
                "dim_headlayers": [50, 25],
                "type": "mlp",
            },
        },
        "task_weights": task_weights,
    }
    if model_type == "CGCNN":
        arch["hidden_dim"] = 1  # CGCNN preserves input width
    if model_type == "SchNet":
        # reference-parity capacity (tests/inputs/ci.json + ci_multihead
        # .json: num_gaussians 50, num_filters 126). This is load-bearing
        # for the multihead cell: the "x" node head asks for the raw node
        # type, which a self-loop-free CFConv stack recovers only through
        # 2-hop backscatter (i->j->i) — at 8 filters that pathway is too
        # narrow and the cell plateaus near 0.21 MAE; at the reference's
        # 126 it trains to ~0.03 RMSE / 0.12 MAE (r05 experiment,
        # docs/PERF.md "SchNet multihead cell").
        arch["num_gaussians"] = 50
        arch["num_filters"] = 126
    return {
        "Verbosity": {"level": 0},
        "Dataset": {
            "name": "unit_test",
            "format": "unit_test",
            "compositional_stratified_splitting": True,
            "rotational_invariance": False,
            "node_features": {
                "name": ["x", "x2", "x3"],
                "dim": [1, 1, 1],
                "column_index": [0, 6, 7],
            },
            "graph_features": {
                "name": ["sum_x_x2_x3"],
                "dim": [1],
                "column_index": [0],
            },
        },
        "NeuralNetwork": {
            "Architecture": arch,
            "Variables_of_interest": voi,
            "Training": {
                "num_epoch": num_epoch,
                "perc_train": 0.7,
                "loss_function_type": "mse",
                "batch_size": 16,
                "EarlyStopping": False,
                "Optimizer": {"type": "AdamW", "learning_rate": 0.01},
            },
        },
        "Visualization": {"create_plots": False},
    }


def unittest_train_model(
    model_type,
    multihead,
    tmp_path,
    num_epoch=40,
    n_conf=300,
    mutate=None,
    thresholds=None,
):
    """Train + predict + threshold assert (reference: unittest_train_model,
    tests/test_graphs.py:24-171). ``mutate(config)`` adjusts the config in
    place (e.g. edge-length features); ``thresholds`` overrides the
    per-model (rmse, mae) table."""
    config = make_config(model_type, multihead, str(tmp_path), num_epoch)
    if mutate is not None:
        mutate(config)
    samples = deterministic_graph_data(number_configurations=n_conf, seed=0)
    log_dir = str(tmp_path) + "/logs/"
    model, state, history, full_config = run_training(
        config, samples=samples, log_dir=log_dir
    )

    # training must have converged on the known function
    thresholds = thresholds or THRESHOLDS[model_type]
    samples2 = deterministic_graph_data(number_configurations=n_conf, seed=0)
    config2 = make_config(model_type, multihead, str(tmp_path), num_epoch)
    if mutate is not None:
        mutate(config2)
    error, error_rmse_task, true_values, predicted_values = run_prediction(
        config2, samples=samples2, log_dir=log_dir
    )
    heads = []
    for ihead in range(model.cfg.num_heads):
        error_head_rmse = float(error_rmse_task[ihead])
        mae = float(np.mean(np.abs(true_values[ihead] - predicted_values[ihead])))
        heads.append({"rmse": error_head_rmse, "mae": mae})
    _report_matrix_case(model_type, multihead, mutate, thresholds, heads)
    for ihead, h in enumerate(heads):
        assert h["rmse"] < thresholds[0], (
            f"{model_type} head {ihead} RMSE {h['rmse']} >= {thresholds[0]}"
        )
        assert h["mae"] < thresholds[1], (
            f"{model_type} head {ihead} sample MAE {h['mae']} >= {thresholds[1]}"
        )
    return history


def _report_matrix_case(model_type, multihead, mutate, thresholds, heads):
    """Append one acceptance-matrix case to HYDRAGNN_MATRIX_REPORT
    (JSONL) — the committed per-round evidence that the full matrix
    trains to the reference thresholds (VERDICT r03 item 2). Appending
    BEFORE the asserts records failures too."""
    path = os.environ.get("HYDRAGNN_MATRIX_REPORT")
    if not path:
        return
    import json

    rec = {
        "model": model_type,
        "multihead": bool(multihead),
        "variant": getattr(mutate, "__name__", None) if mutate else "default",
        "thresholds_rmse_mae": list(thresholds),
        "heads": heads,
        "ok": all(
            h["rmse"] < thresholds[0] and h["mae"] < thresholds[1] for h in heads
        ),
    }
    with open(path, "a") as f:
        f.write(json.dumps(rec) + "\n")


@pytest.mark.parametrize("model_type", ["GIN", "PNA"])
def pytest_train_model_singlehead(model_type, tmp_path):
    unittest_train_model(model_type, False, tmp_path)


def pytest_train_model_multihead(tmp_path):
    unittest_train_model("PNA", True, tmp_path)


def pytest_model_loadpred(tmp_path):
    """Checkpoint save/load/config round-trip: train briefly, reload via
    run_prediction, assert test MAE < 0.2 (reference:
    tests/test_model_loadpred.py:18-91)."""
    config = make_config("PNA", True, str(tmp_path), num_epoch=35)
    samples = deterministic_graph_data(number_configurations=300, seed=0)
    log_dir = str(tmp_path) + "/logs/"
    run_training(config, samples=samples, log_dir=log_dir)

    config2 = make_config("PNA", True, str(tmp_path), num_epoch=35)
    samples2 = deterministic_graph_data(number_configurations=300, seed=0)
    error, error_rmse_task, true_values, predicted_values = run_prediction(
        config2, samples=samples2, log_dir=log_dir
    )
    for ihead in range(len(true_values)):
        mae = float(np.mean(np.abs(true_values[ihead] - predicted_values[ihead])))
        assert mae < 0.2, f"head {ihead} MAE {mae} >= 0.2"


# 15-epoch smoke thresholds with ~2x margin over measured landing spots
# (SAGE .03/.13, GAT .03/.12, MFC .15/.31, CGCNN .19/.33, SchNet .15/.25
# at lr 0.02, batch 32, 150 configs — deterministic seeds). Purpose:
# catch TRAINING-DYNAMICS regressions in the flavors the fast pass
# doesn't train to full accuracy; the reference-threshold runs live in
# test_train_matrix.py behind HYDRAGNN_FULL_MATRIX=1.
SMOKE_THRESHOLDS = {
    "SAGE": [0.10, 0.25],
    "GAT": [0.12, 0.25],
    "MFC": [0.30, 0.50],
    "CGCNN": [0.40, 0.55],
    "SchNet": [0.30, 0.45],
}


def _smoke_budget(config):
    config["NeuralNetwork"]["Training"]["batch_size"] = 32
    config["NeuralNetwork"]["Training"]["Optimizer"]["learning_rate"] = 0.02


@pytest.mark.parametrize("model_type", sorted(SMOKE_THRESHOLDS))
def pytest_train_model_smoke(model_type, tmp_path):
    """Every conv flavor trains briefly in the DEFAULT suite (GIN/PNA
    already train to reference thresholds above)."""
    unittest_train_model(
        model_type,
        False,
        tmp_path,
        num_epoch=15,
        n_conf=150,
        mutate=_smoke_budget,
        thresholds=SMOKE_THRESHOLDS[model_type],
    )
