"""Opt-in real-chip TPU kernel suite (VERDICT r02 item 3).

The in-process pytest session pins a virtual CPU mesh before jax loads
(conftest), so the on-chip checks run in a SUBPROCESS with a clean
environment where the image's default backend (the tunneled TPU) wins.
Gated behind HYDRAGNN_TPU_TESTS=1: the checks dispatch against the real
chip and are budgeted under its post-burst throttle (~40 dispatches).

Run via ``CI_TPU=1 ./ci.sh`` or directly:
``HYDRAGNN_TPU_TESTS=1 python -m pytest tests/test_tpu_chip.py -q``.
"""

import os
import subprocess
import sys

import pytest

pytestmark = pytest.mark.skipif(
    os.environ.get("HYDRAGNN_TPU_TESTS") != "1",
    reason="real-chip suite: set HYDRAGNN_TPU_TESTS=1 (needs a TPU)",
)

_REPO = os.path.join(os.path.dirname(os.path.abspath(__file__)), os.pardir)


def pytest_tpu_kernel_selfcheck():
    env = dict(os.environ)
    # drop any CPU pin the caller exported; the subprocess must see the
    # image default (axon TPU plugin)
    env.pop("JAX_PLATFORMS", None)
    env.pop("XLA_FLAGS", None)
    env.pop("HYDRAGNN_PALLAS", None)
    proc = subprocess.run(
        [sys.executable, "-m", "hydragnn_tpu.tools.tpu_selfcheck"],
        cwd=_REPO,
        env=env,
        capture_output=True,
        text=True,
        timeout=1800,
    )
    sys.stdout.write(proc.stdout)
    sys.stderr.write(proc.stderr[-2000:])
    assert proc.returncode == 0, f"on-chip selfcheck failed (rc={proc.returncode})"
