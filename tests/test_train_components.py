"""Unit tests for train-layer components: optimizer factory, dynamic LR,
plateau scheduler, early stopping, freeze mask, checkpoint round-trip.

Interface-parity model: the reference smoke-tests every optimizer flavor
(reference: tests/test_optimizer.py:23-113) and loss flavor
(tests/test_loss.py:22-100) by running 2 epochs; here the optimizer matrix
runs one jitted step each, plus direct asserts on scheduler/stopper
semantics the reference delegates to torch.
"""

import numpy as np
import jax
import jax.numpy as jnp
import optax
import pytest

from hydragnn_tpu.data.synthetic import deterministic_graph_data
from hydragnn_tpu.data.ingest import prepare_dataset
from hydragnn_tpu.data.loader import GraphLoader
from hydragnn_tpu.models.create import create_model_config
from hydragnn_tpu.train import (
    EarlyStopping,
    ReduceLROnPlateau,
    create_train_state,
    current_learning_rate,
    make_eval_step,
    make_train_step,
    select_optimizer,
    set_learning_rate,
)
from hydragnn_tpu.train.optimizer import OPTIMIZERS
from hydragnn_tpu.utils.checkpoint import load_existing_model, save_model
from hydragnn_tpu.utils.config import update_config

from test_data_pipeline import base_config


@pytest.fixture(scope="module")
def small_problem():
    cfg = base_config(multihead=False)
    cfg["NeuralNetwork"]["Architecture"]["model_type"] = "GIN"
    samples = deterministic_graph_data(number_configurations=40, seed=3)
    train, val, test, _, _ = prepare_dataset(samples, cfg)
    cfg = update_config(cfg, train, val, test)
    loader = GraphLoader(train, 8, shuffle=True)
    example = next(iter(loader))
    model, variables = create_model_config(cfg["NeuralNetwork"], example)
    return cfg, model, variables, example


@pytest.mark.parametrize("opt_type", OPTIMIZERS)
def pytest_optimizer_types_one_step(small_problem, opt_type):
    cfg, model, variables, batch = small_problem
    tx = select_optimizer({"Optimizer": {"type": opt_type, "learning_rate": 1e-3}})
    state = create_train_state(variables, tx)
    step = make_train_step(model, tx)
    new_state, loss, tasks = step(state, batch)
    assert np.isfinite(float(loss))
    assert int(new_state.step) == 1


@pytest.mark.parametrize("loss_type", ["mse", "mae", "rmse"])
def pytest_loss_types_one_step(small_problem, loss_type):
    cfg, model, variables, batch = small_problem
    import dataclasses

    model2 = type(model)(dataclasses.replace(model.cfg, loss_function_type=loss_type))
    tx = select_optimizer({"Optimizer": {"type": "AdamW", "learning_rate": 1e-3}})
    state = create_train_state(variables, tx)
    step = make_train_step(model2, tx)
    _, loss, _ = step(state, batch)
    assert np.isfinite(float(loss))


def pytest_unknown_optimizer_raises():
    with pytest.raises(NameError):
        select_optimizer({"Optimizer": {"type": "Nope", "learning_rate": 1e-3}}).init({})


def pytest_dynamic_learning_rate(small_problem):
    cfg, model, variables, batch = small_problem
    tx = select_optimizer({"Optimizer": {"type": "AdamW", "learning_rate": 0.01}})
    state = create_train_state(variables, tx)
    assert current_learning_rate(state.opt_state) == pytest.approx(0.01)
    state = state.replace(opt_state=set_learning_rate(state.opt_state, 0.005))
    assert current_learning_rate(state.opt_state) == pytest.approx(0.005)
    # changed lr must not retrigger compilation (same shapes/dtypes)
    step = make_train_step(model, tx)
    step(state, batch)


def pytest_freeze_conv_zeroes_conv_updates(small_problem):
    cfg, model, variables, batch = small_problem
    tx = select_optimizer(
        {"Optimizer": {"type": "SGD", "learning_rate": 0.1}}, freeze_conv=True
    )
    state = create_train_state(variables, tx)
    step = make_train_step(model, tx)
    params_before = jax.device_get(state.params)  # step() donates state
    new_state, _, _ = step(state, batch)
    for key, sub in params_before.items():
        before = jax.tree_util.tree_leaves(sub)
        after = jax.tree_util.tree_leaves(new_state.params[key])
        same = all(np.allclose(b, a) for b, a in zip(before, after))
        if key.startswith("conv_"):
            assert same, f"frozen conv subtree {key} changed"
        elif key.startswith("graph_head") or key == "graph_shared":
            assert not same, f"trainable subtree {key} did not change"


def pytest_reduce_lr_on_plateau(small_problem):
    cfg, model, variables, batch = small_problem
    tx = select_optimizer({"Optimizer": {"type": "AdamW", "learning_rate": 0.01}})
    state = create_train_state(variables, tx)
    sched = ReduceLROnPlateau(factor=0.5, patience=2, min_lr=1e-5)
    state = sched.step(state, 1.0)  # best
    for _ in range(2):  # bad epochs within patience
        state = sched.step(state, 2.0)
        assert current_learning_rate(state.opt_state) == pytest.approx(0.01)
    state = sched.step(state, 2.0)  # exceeds patience -> halve
    assert current_learning_rate(state.opt_state) == pytest.approx(0.005)
    # floor at min_lr
    for _ in range(40):
        state = sched.step(state, 2.0)
    assert current_learning_rate(state.opt_state) == pytest.approx(1e-5, rel=1e-5)


def pytest_early_stopping_semantics():
    stopper = EarlyStopping(patience=3)
    assert not stopper(1.0)
    assert not stopper(0.9)  # improvement resets
    assert not stopper(1.1)
    assert not stopper(1.1)
    assert stopper(1.1)  # third bad epoch


def pytest_checkpoint_roundtrip(small_problem, tmp_path):
    cfg, model, variables, batch = small_problem
    tx = select_optimizer({"Optimizer": {"type": "AdamW", "learning_rate": 0.01}})
    state = create_train_state(variables, tx)
    step = make_train_step(model, tx)
    state, _, _ = step(state, batch)
    save_model(state, "ckpt_test", str(tmp_path) + "/")

    fresh = create_train_state(variables, tx)
    restored = load_existing_model(fresh, "ckpt_test", str(tmp_path) + "/")
    assert int(restored.step) == 1
    for a, b in zip(
        jax.tree_util.tree_leaves(state.params),
        jax.tree_util.tree_leaves(restored.params),
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b))
    # restored state must produce identical eval outputs
    ev = make_eval_step(model)
    l1, _ = ev(state, batch)
    l2, _ = ev(restored, batch)
    assert float(l1) == pytest.approx(float(l2), rel=1e-6)


def pytest_mixed_precision_step_trains():
    """bf16 compute path: finite loss that decreases, f32 master state
    and BatchNorm statistics preserved."""
    import jax
    import jax.numpy as jnp
    from hydragnn_tpu.flagship import build_flagship
    from hydragnn_tpu.train import (
        create_train_state,
        make_train_step,
        select_optimizer,
    )

    config, model, variables, loader = build_flagship(
        n_samples=48, hidden_dim=16, num_conv_layers=2, batch_size=8
    )
    tx = select_optimizer(config["NeuralNetwork"]["Training"])
    state = create_train_state(variables, tx)
    step = make_train_step(model, tx, compute_dtype=jnp.bfloat16)
    batches = list(loader)
    first = None
    for epoch in range(6):
        for b in batches:
            state, loss, _ = step(state, b)
            if first is None:
                first = float(loss)
    last = float(loss)
    assert np.isfinite(last)
    assert last < first
    # master params and BN stats stay f32
    for leaf in jax.tree_util.tree_leaves(state.params):
        assert leaf.dtype == jnp.float32
    for leaf in jax.tree_util.tree_leaves(state.batch_stats):
        assert leaf.dtype == jnp.float32


def pytest_per_split_raw_paths(tmp_path):
    """Dataset.path.{train,validate,test} layout: pre-defined split
    membership, normalization spanning all splits (reference:
    load_data.py:352-393)."""
    from hydragnn_tpu.api import prepare_loaders_and_config
    from hydragnn_tpu.data.synthetic import write_lsms_files

    counts = {"train": 30, "validate": 10, "test": 10}
    paths = {}
    start = 0
    for split_idx, (key, n) in enumerate(counts.items()):
        d = tmp_path / key
        write_lsms_files(str(d), number_configurations=n,
                         configuration_start=start, seed=split_idx)
        paths[key] = str(d)
        start += n

    config = {
        "Verbosity": {"level": 0},
        "Dataset": {
            "name": "unit_test",
            "format": "unit_test",
            "path": paths,
            "compositional_stratified_splitting": False,
            "rotational_invariance": False,
            "node_features": {
                "name": ["x", "x2", "x3"],
                "dim": [1, 1, 1],
                "column_index": [0, 6, 7],
            },
            "graph_features": {
                "name": ["sum_x_x2_x3"], "dim": [1], "column_index": [0],
            },
        },
        "NeuralNetwork": {
            "Architecture": {
                "model_type": "GIN",
                "radius": 2.0,
                "max_neighbours": 100,
                "hidden_dim": 8,
                "num_conv_layers": 2,
                "output_heads": {
                    "graph": {
                        "num_sharedlayers": 1, "dim_sharedlayers": 5,
                        "num_headlayers": 1, "dim_headlayers": [10],
                    }
                },
                "task_weights": [1.0],
            },
            "Variables_of_interest": {
                "input_node_features": [0],
                "output_names": ["sum_x_x2_x3"],
                "output_index": [0],
                "type": ["graph"],
            },
            "Training": {
                "num_epoch": 1,
                "perc_train": 0.7,
                "loss_function_type": "mse",
                "batch_size": 8,
                "Optimizer": {"type": "AdamW", "learning_rate": 0.01},
            },
        },
        "Visualization": {"create_plots": False},
    }
    train_loader, val_loader, test_loader, config = prepare_loaders_and_config(config)
    assert train_loader.num_samples == counts["train"]
    assert val_loader.num_samples == counts["validate"]
    assert test_loader.num_samples == counts["test"]


def pytest_config_gated_profiler_writes_trace(tmp_path):
    """NeuralNetwork.Profile.enable drives an epoch-gated jax.profiler
    trace from the train loop (reference: train_validate_test.py:99-101)."""
    import glob

    from hydragnn_tpu.api import run_training
    from hydragnn_tpu.data.synthetic import deterministic_graph_data

    # 200 configs -> ~140 train samples -> 18 batches/epoch, comfortably
    # above the profiler schedule's wait+warmup+active = 11 steps
    samples = deterministic_graph_data(number_configurations=200)
    config = {
        "Verbosity": {"level": 0},
        "Dataset": {
            "name": "prof",
            "format": "unit_test",
            "node_features": {"name": ["x", "x2", "x3"], "dim": [1, 1, 1],
                              "column_index": [0, 6, 7]},
            "graph_features": {"name": ["sum"], "dim": [1], "column_index": [0]},
        },
        "NeuralNetwork": {
            "Profile": {"enable": 1, "target_epoch": 1},
            "Architecture": {
                "model_type": "GIN", "radius": 2.0, "max_neighbours": 100,
                "hidden_dim": 8, "num_conv_layers": 1,
                "output_heads": {"graph": {"num_sharedlayers": 1,
                    "dim_sharedlayers": 5, "num_headlayers": 1,
                    "dim_headlayers": [10]}},
                "task_weights": [1.0],
            },
            "Variables_of_interest": {
                "input_node_features": [0], "output_names": ["sum"],
                "output_index": [0], "type": ["graph"],
            },
            "Training": {
                "num_epoch": 2, "perc_train": 0.7, "loss_function_type": "mse",
                "batch_size": 8, "Optimizer": {"type": "AdamW", "learning_rate": 0.01},
            },
        },
        "Visualization": {"create_plots": False},
    }
    run_training(config, samples=samples, log_dir=str(tmp_path) + "/logs/")
    artifacts = glob.glob(
        str(tmp_path) + "/logs/**/profile/**/*", recursive=True
    )
    assert artifacts, "Profile.enable must produce profiler artifacts"


def pytest_print_peak_memory_smoke(capsys):
    """print_peak_memory (reference: hydragnn/utils/distributed.py:236-243)
    must return the peak byte count where the backend exposes memory_stats
    and None (silently) where it doesn't — never raise. It's wired into
    train_validate_test after epoch 0."""
    from hydragnn_tpu.utils.print_utils import print_peak_memory

    peak = print_peak_memory(verbosity_level=4, prefix="smoke")
    out = capsys.readouterr().out
    if peak is None:
        assert "peak device memory" not in out
    else:
        assert peak >= 0
        assert "peak device memory" in out


def pytest_remat_step_matches_plain(small_problem):
    """Training.remat trades FLOPs for memory; it must be numerically a
    no-op: one rematerialized step produces the same loss and parameter
    update as the plain step."""
    import jax

    cfg, model, variables, example = small_problem
    tx = select_optimizer({"Optimizer": {"type": "AdamW", "learning_rate": 0.01}})

    results = []
    for remat in (False, True):
        state = create_train_state(variables, tx, seed=0)
        step = make_train_step(model, tx, remat=remat)
        state, loss, tasks = step(state, example)
        results.append((float(loss), state.params))
    assert np.isfinite(results[0][0])
    np.testing.assert_allclose(results[0][0], results[1][0], rtol=1e-6)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-6, atol=1e-7
        ),
        results[0][1],
        results[1][1],
    )


def pytest_grad_accum_steps(small_problem):
    """Training.grad_accum_steps=k must hold parameters fixed for k-1
    micro-steps, apply the averaged update on the k-th, and keep the
    dynamic-LR plumbing (plateau scheduler) working through the wrapper."""
    import jax

    from hydragnn_tpu.train.optimizer import (
        current_learning_rate,
        set_learning_rate,
    )

    cfg, model, variables, example = small_problem
    tx = select_optimizer(
        {"Optimizer": {"type": "SGD", "learning_rate": 0.05}, "grad_accum_steps": 2}
    )
    state = create_train_state(variables, tx, seed=0)
    step = make_train_step(model, tx)
    p0 = jax.device_get(state.params)

    state, loss1, _ = step(state, example)
    p1 = jax.device_get(state.params)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
        p0,
        p1,
    )  # micro-step 1: accumulate only

    state, loss2, _ = step(state, example)
    p2 = jax.device_get(state.params)
    changed = any(
        not np.array_equal(np.asarray(a), np.asarray(b))
        for a, b in zip(
            jax.tree_util.tree_leaves(p1), jax.tree_util.tree_leaves(p2)
        )
    )
    assert changed, "second micro-step must apply the accumulated update"
    assert np.isfinite(float(loss1)) and np.isfinite(float(loss2))

    # LR read/write through the MultiSteps wrapper
    assert current_learning_rate(state.opt_state) == pytest.approx(0.05)
    state = state.replace(opt_state=set_learning_rate(state.opt_state, 0.025))
    assert current_learning_rate(state.opt_state) == pytest.approx(0.025)


def pytest_scan_epoch_matches_sequential(small_problem):
    """One scan-epoch dispatch must produce the same final params and
    weighted loss as stepping the same batches sequentially."""
    import jax
    import jax.numpy as jnp

    from hydragnn_tpu.train import make_scan_epoch

    cfg, model, variables, _ = small_problem
    samples = deterministic_graph_data(number_configurations=40, seed=3)
    train, _, _, _, _ = prepare_dataset(samples, base_config(multihead=False))
    loader = GraphLoader(train, 8, shuffle=False)
    tx = select_optimizer({"Optimizer": {"type": "AdamW", "learning_rate": 0.01}})

    # sequential
    state_seq = create_train_state(variables, tx, seed=0)
    step = make_train_step(model, tx)
    losses_seq, counts = [], []
    for batch in loader:
        state_seq, loss, _ = step(state_seq, batch)
        losses_seq.append(float(loss))
        counts.append(float(np.asarray(batch.graph_mask).sum()))

    # one scan dispatch
    state_scan = create_train_state(variables, tx, seed=0)
    scan_fn = make_scan_epoch(model, tx)
    stacked = loader.stacked_device_batches()
    order = jnp.arange(len(loader), dtype=jnp.int32)
    state_scan, losses, tasks, cnts = scan_fn(state_scan, stacked, order)

    np.testing.assert_allclose(np.asarray(losses), losses_seq, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(cnts), counts)
    for a, b in zip(
        jax.tree_util.tree_leaves(jax.device_get(state_seq.params)),
        jax.tree_util.tree_leaves(jax.device_get(state_scan.params)),
    ):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)


def pytest_scan_epoch_run_training(tmp_path):
    """Training.scan_epoch=True through the full run_training pipeline:
    converges like the streaming path and writes the same artifacts."""
    from hydragnn_tpu.api import run_training
    from test_train_e2e import make_config

    config = make_config("GIN", False, str(tmp_path), num_epoch=12)
    config["NeuralNetwork"]["Training"]["scan_epoch"] = True
    samples = deterministic_graph_data(number_configurations=120, seed=0)
    _, _, history, _ = run_training(
        config, samples=samples, log_dir=str(tmp_path) + "/logs/"
    )
    losses = history["train_loss"]
    assert all(np.isfinite(losses))
    assert min(losses) < 0.5 * losses[0], losses


def pytest_scan_eval_matches_sequential(small_problem):
    """One scan-eval dispatch must equal per-batch evaluation."""
    from hydragnn_tpu.train import make_eval_step
    from hydragnn_tpu.train.state import make_scan_eval
    from hydragnn_tpu.train.loop import evaluate_epoch, evaluate_epoch_scan

    cfg, model, variables, _ = small_problem
    samples = deterministic_graph_data(number_configurations=40, seed=3)
    train, _, _, _, _ = prepare_dataset(samples, base_config(multihead=False))
    loader = GraphLoader(train, 8, shuffle=False)
    tx = select_optimizer({"Optimizer": {"type": "AdamW", "learning_rate": 0.01}})
    state = create_train_state(variables, tx, seed=0)

    seq_loss, seq_tasks = evaluate_epoch(loader, state, make_eval_step(model))
    scan_loss, scan_tasks = evaluate_epoch_scan(loader, state, make_scan_eval(model))
    np.testing.assert_allclose(scan_loss, seq_loss, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(scan_tasks, seq_tasks, rtol=1e-5, atol=1e-6)


def pytest_checkpoint_resume_exact(tmp_path):
    """Per-epoch checkpointing (Training.checkpoint_every) + continue must
    resume EXACTLY: an interrupted-at-3-then-resumed-to-6 run reproduces
    the uninterrupted 6-epoch run's history and parameters (rng chain,
    epoch-seeded shuffles, scheduler and early-stop counters all survive
    the restart). The reference restores only model+optimizer and restarts
    epoch numbering (SURVEY §5)."""
    from hydragnn_tpu.api import run_training
    from hydragnn_tpu.utils.config import get_log_name_config
    from test_train_e2e import make_config

    def fresh_samples():
        # the ingest pipeline mutates the sample list in place; every run
        # gets an identical fresh copy (same seed)
        return deterministic_graph_data(number_configurations=80, seed=0)

    def cfg_for(num_epoch):
        c = make_config("GIN", False, str(tmp_path), num_epoch=num_epoch)
        t = c["NeuralNetwork"]["Training"]
        t["bn_recalibration"] = False  # final recal would diverge from the mid-run save
        t["checkpoint_every"] = 1
        return c

    # uninterrupted reference run
    _, state_a, hist_a, _ = run_training(
        cfg_for(6), samples=fresh_samples(), log_dir=str(tmp_path) + "/a/"
    )

    # interrupted at 3 ...
    _, _, hist_b, full_b = run_training(
        cfg_for(3), samples=fresh_samples(), log_dir=str(tmp_path) + "/b/"
    )
    name_b = get_log_name_config(full_b)

    # ... resumed to 6 in the same log dir
    cfg_c = cfg_for(6)
    cfg_c["NeuralNetwork"]["Training"]["continue"] = 1
    cfg_c["NeuralNetwork"]["Training"]["startfrom"] = name_b
    _, state_c, hist_c, _ = run_training(
        cfg_c, samples=fresh_samples(), log_dir=str(tmp_path) + "/b/"
    )

    assert len(hist_c["train_loss"]) == 6
    np.testing.assert_allclose(hist_c["train_loss"][:3], hist_b["train_loss"], rtol=1e-6)
    np.testing.assert_allclose(
        hist_c["train_loss"], hist_a["train_loss"], rtol=1e-5, atol=1e-7
    )
    for a, b in zip(
        jax.tree_util.tree_leaves(jax.device_get(state_a.params)),
        jax.tree_util.tree_leaves(jax.device_get(state_c.params)),
    ):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-7)


def pytest_device_stack_fallback_warns():
    """A batch size that doesn't divide the local device count must fall
    back to single-device LOUDLY (silent 8x throughput loss otherwise)."""
    from hydragnn_tpu.api import _choose_device_stack

    n_local = jax.local_device_count()
    assert n_local > 1  # conftest pins the 8-device CPU mesh

    cfg = {"NeuralNetwork": {"Training": {"batch_size": n_local + 1}}}
    with pytest.warns(RuntimeWarning, match="SINGLE-DEVICE"):
        assert _choose_device_stack(cfg) == 1

    cfg_ok = {"NeuralNetwork": {"Training": {"batch_size": 2 * n_local}}}
    assert _choose_device_stack(cfg_ok) == n_local


def pytest_scan_reshuffle_membership():
    """scan_reshuffle_every=k rebuilds sample-to-batch membership every k
    epochs (reference DataLoader(shuffle=True) parity for the scan path);
    the default keeps the one-time stack."""
    samples = deterministic_graph_data(number_configurations=40, seed=3)
    train, _, _, _, _ = prepare_dataset(samples, base_config(multihead=False))

    frozen = GraphLoader(train, 8, shuffle=True)
    s0 = frozen.stacked_device_batches(0)
    s1 = frozen.stacked_device_batches(1)
    assert s0 is s1  # built once, membership fixed

    reshuf = GraphLoader(train, 8, shuffle=True, scan_reshuffle_every=1)
    r0 = reshuf.stacked_device_batches(0)
    r1 = reshuf.stacked_device_batches(1)
    assert r0 is not r1
    assert not np.array_equal(np.asarray(r0.nodes), np.asarray(r1.nodes))
    # same epoch -> same membership (cached, no rebuild churn)
    assert reshuf.stacked_device_batches(1) is r1
    # every sample appears exactly once regardless of membership shuffle
    for st in (r0, r1):
        n_real = int(np.asarray(st.node_mask).sum())
        assert n_real == sum(s.num_nodes for s in train)


def pytest_resume_noop_is_pure(tmp_path):
    """Resuming a completed run (start_epoch >= num_epoch) must not touch
    the saved checkpoint: no BN recalibration, no rewrite."""
    import os

    from hydragnn_tpu.api import run_training
    from hydragnn_tpu.utils.config import get_log_name_config
    from test_train_e2e import make_config

    def fresh_samples():
        return deterministic_graph_data(number_configurations=80, seed=0)

    cfg = make_config("GIN", False, str(tmp_path), num_epoch=3)
    cfg["NeuralNetwork"]["Training"]["checkpoint_every"] = 1
    _, _, hist, full = run_training(
        cfg, samples=fresh_samples(), log_dir=str(tmp_path) + "/logs/"
    )
    name = get_log_name_config(full)
    model_files = [
        os.path.join(str(tmp_path), "logs", name, f)
        for f in os.listdir(os.path.join(str(tmp_path), "logs", name))
        if f.endswith((".msgpack", ".meta.json"))
    ]
    assert model_files
    before = {p: open(p, "rb").read() for p in model_files}

    cfg2 = make_config("GIN", False, str(tmp_path), num_epoch=3)
    cfg2["NeuralNetwork"]["Training"]["checkpoint_every"] = 1
    cfg2["NeuralNetwork"]["Training"]["continue"] = 1
    cfg2["NeuralNetwork"]["Training"]["startfrom"] = name
    _, _, hist2, _ = run_training(
        cfg2, samples=fresh_samples(), log_dir=str(tmp_path) + "/logs/"
    )
    assert len(hist2["train_loss"]) == len(hist["train_loss"])
    for p, content in before.items():
        assert open(p, "rb").read() == content, f"no-op resume rewrote {p}"


def pytest_meta_step_mismatch_rederives_epoch(tmp_path):
    """A meta sidecar older than the weights (crash between the two
    writes) must not replay epochs on the newer weights: resume derives
    the epoch from the weights' optimizer step instead."""
    import json
    import os

    from hydragnn_tpu.api import run_training
    from hydragnn_tpu.utils.config import get_log_name_config
    from test_train_e2e import make_config

    def fresh_samples():
        return deterministic_graph_data(number_configurations=80, seed=0)

    cfg = make_config("GIN", False, str(tmp_path), num_epoch=4)
    cfg["NeuralNetwork"]["Training"]["checkpoint_every"] = 1
    cfg["NeuralNetwork"]["Training"]["bn_recalibration"] = False
    _, state, hist, full = run_training(
        cfg, samples=fresh_samples(), log_dir=str(tmp_path) + "/logs/"
    )
    name = get_log_name_config(full)
    meta_path = os.path.join(str(tmp_path), "logs", name, f"{name}.meta.json")
    meta = json.load(open(meta_path))

    # simulate the crash: meta describes epoch 2 / half the steps, while
    # the weight file stays at its final (epoch-4) state
    meta["epoch"] = 2
    meta["step"] = meta["step"] // 2
    meta["history"] = {k: v[:2] for k, v in meta["history"].items()}
    json.dump(meta, open(meta_path, "w"))

    cfg2 = make_config("GIN", False, str(tmp_path), num_epoch=4)
    cfg2["NeuralNetwork"]["Training"]["checkpoint_every"] = 1
    cfg2["NeuralNetwork"]["Training"]["bn_recalibration"] = False
    cfg2["NeuralNetwork"]["Training"]["continue"] = 1
    cfg2["NeuralNetwork"]["Training"]["startfrom"] = name
    _, state2, hist2, _ = run_training(
        cfg2, samples=fresh_samples(), log_dir=str(tmp_path) + "/logs/"
    )
    # epoch re-derived from the weights' step (4 full epochs) -> no replay
    for a, b in zip(
        jax.tree_util.tree_leaves(jax.device_get(state.params)),
        jax.tree_util.tree_leaves(jax.device_get(state2.params)),
    ):
        np.testing.assert_array_equal(a, b)
    # history re-aligned to the derived epoch and the sidecar repaired
    assert len(hist2["train_loss"]) == 4
    repaired = json.load(open(meta_path))
    assert repaired["epoch"] == 4
    assert repaired["step"] == meta["step"] * 2
