"""tools/export_to_reference_pickle.py: the HGC -> reference
sharded-pickle exporter must round-trip through the importer
(data/import_reference.py) — the committed proof of the two-way
migration story (docs/MIGRATION.md)."""

import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), os.pardir, "tools"))

from export_to_reference_pickle import (  # noqa: E402
    export_container,
    export_samples_to_pickles,
    sample_to_reference_dict,
)

from hydragnn_tpu.data.container import ContainerDataset, ContainerWriter
from hydragnn_tpu.data.dataset import GraphSample
from hydragnn_tpu.data.import_reference import (
    ReferencePickleReader,
    import_pickle_dataset,
)


def _samples(n=6, seed=0):
    rng = np.random.default_rng(seed)
    out = []
    for i in range(n):
        nn = 4 + i % 3
        src = np.arange(nn - 1, dtype=np.int64)
        ei = np.stack(
            [np.concatenate([src, src + 1]), np.concatenate([src + 1, src])]
        )
        out.append(
            GraphSample(
                x=rng.normal(size=(nn, 3)).astype(np.float32),
                pos=rng.normal(size=(nn, 3)).astype(np.float32),
                edge_index=ei.astype(np.int32),
                edge_attr=np.ones((ei.shape[1], 2), np.float32) * i,
                graph_targets={"energy": np.asarray([float(i)], np.float32)},
                node_targets={"charge": rng.normal(size=(nn, 2)).astype(np.float32)},
            )
        )
    return out


def _assert_sample_equal(a: GraphSample, b: GraphSample):
    np.testing.assert_allclose(a.x, b.x)
    np.testing.assert_allclose(a.pos, b.pos)
    np.testing.assert_array_equal(a.edge_index, b.edge_index)
    np.testing.assert_allclose(a.edge_attr, b.edge_attr)
    assert sorted(a.graph_targets) == sorted(b.graph_targets)
    for k in a.graph_targets:
        np.testing.assert_allclose(
            np.asarray(a.graph_targets[k]).reshape(-1),
            np.asarray(b.graph_targets[k]).reshape(-1),
        )
    assert sorted(a.node_targets) == sorted(b.node_targets)
    for k in a.node_targets:
        np.testing.assert_allclose(a.node_targets[k], b.node_targets[k])


def pytest_packed_y_layout_matches_reference_contract():
    s = _samples(1)[0]
    d = sample_to_reference_dict(s)
    # graph heads first (sorted), then node heads: y_loc marks the rows
    assert d["y_loc"].tolist() == [0, 1, 1 + s.x.shape[0] * 2]
    np.testing.assert_allclose(d["y"][:1], s.graph_targets["energy"])
    np.testing.assert_allclose(
        d["y"][1:].reshape(s.x.shape[0], 2), s.node_targets["charge"]
    )
    assert d["edge_index"].shape[0] == 2


def pytest_container_export_import_round_trip(tmp_path):
    samples = _samples()
    src = str(tmp_path / "src.hgc")
    w = ContainerWriter(src)
    w.add(samples)
    w.add_global("minmax_node_feature", [[0.0, 1.0]])
    w.add_global("minmax_graph_feature", [[0.0, 2.0]])
    w.save()

    outdir = str(tmp_path / "pickles")
    n, names, types = export_container(src, outdir, "trainset")
    assert n == len(samples)
    assert names == ["energy", "charge"] and types == ["graph", "node"]

    # the reference reader sees the layout it expects
    reader = ReferencePickleReader(outdir, "trainset")
    assert len(reader) == len(samples)
    np.testing.assert_allclose(
        np.asarray(reader.minmax_graph_feature), [[0.0, 2.0]]
    )

    # full round trip back through the importer into a second container
    back = str(tmp_path / "back.hgc")
    count = import_pickle_dataset(
        outdir, "trainset", back, head_types=types, head_names=names
    )
    assert count == len(samples)
    ds = ContainerDataset(back)
    try:
        assert len(ds) == len(samples)
        for i, s in enumerate(samples):
            _assert_sample_equal(s, ds.get(i))
        mm_g, mm_n = ds.minmax()
        np.testing.assert_allclose(mm_g, [[0.0, 2.0]])
        np.testing.assert_allclose(mm_n, [[0.0, 1.0]])
    finally:
        ds.close()


def pytest_subdir_layout_round_trips(tmp_path):
    samples = _samples(5, seed=1)
    outdir = str(tmp_path / "pickles")
    n, names, types = export_samples_to_pickles(
        samples, outdir, "total", nmax_persubdir=2
    )
    assert n == 5
    assert os.path.isdir(os.path.join(outdir, "0"))  # samples 0-1
    assert os.path.isdir(os.path.join(outdir, "2"))  # sample 4
    reader = ReferencePickleReader(outdir, "total")
    got = reader.samples(head_types=types, head_names=names)
    for s, g in zip(samples, got):
        _assert_sample_equal(s, g)
