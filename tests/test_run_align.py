"""Run-aligned edge layout (graph/batch.py run_align) + local-window
kernel correctness.

The aligned layout changes the EDGE STRUCTURE (masked self-loop padding
inside receiver runs) while every masked aggregation must stay
numerically equivalent to the plain layout; these tests pin that
equivalence at the loader, op, and full-train-step levels (the chip A/B
measured the speed — tools/ab_align.py; docs/PERF.md r04)."""

import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from hydragnn_tpu.data.ingest import prepare_dataset
from hydragnn_tpu.data.loader import GraphLoader
from hydragnn_tpu.data.synthetic import deterministic_graph_data
from hydragnn_tpu.flagship import flagship_config
from hydragnn_tpu.graph.batch import _block_windows, batch_graphs
from hydragnn_tpu.models.create import create_model_config
from hydragnn_tpu.train import create_train_state, make_train_step, select_optimizer
from hydragnn_tpu.utils.config import update_config


def _random_graphs(n_graphs=6, seed=0):
    rng = np.random.default_rng(seed)
    gs = []
    for _ in range(n_graphs):
        n = int(rng.integers(5, 11))
        deg = rng.integers(0, 5, n)
        s, r = [], []
        for node in range(n):
            for _ in range(deg[node]):
                s.append(int(rng.integers(0, n)))
                r.append(node)
        if not s:  # keep at least one edge per graph
            s, r = [0], [1 % n]
        gs.append(
            {
                "x": rng.standard_normal((n, 3)),
                "senders": np.array(s),
                "receivers": np.array(r),
                "edge_attr": rng.standard_normal((len(s), 2)),
                "graph_targets": {"e": rng.standard_normal(1)},
            }
        )
    return gs


def test_aligned_layout_invariants_and_agg_equivalence():
    gs = _random_graphs()
    b0 = batch_graphs(gs, dense_slots=None)
    b8 = batch_graphs(
        gs,
        dense_slots=None,
        run_align=4,
        n_edge_pad=((b0.num_edges + sum(g["x"].shape[0] for g in gs) * 4) // 4 + 1) * 4,
    )
    b8.check_invariants()
    assert b8.run_align == 4

    # masked aggregation equivalence on real nodes
    def agg(b):
        d = jnp.where(b.edge_mask[:, None], b.edge_attr, 0)
        out = jax.ops.segment_sum(d, b.receivers, b.num_nodes)
        return np.asarray(out)[np.asarray(b.node_mask)]

    np.testing.assert_allclose(agg(b0), agg(b8), rtol=1e-6)
    # real in-degree equivalence
    d0 = np.asarray(b0.in_degree)[np.asarray(b0.node_mask)]
    d8 = np.asarray(b8.in_degree)[np.asarray(b8.node_mask)]
    np.testing.assert_array_equal(d0, d8)
    # every K-group shares one receiver among its REAL slots
    K = b8.run_align
    recv = np.asarray(b8.receivers).reshape(-1, K)
    m = np.asarray(b8.edge_mask).reshape(-1, K)
    for row, mr in zip(recv, m):
        if mr.any():
            assert len(set(row[mr])) == 1
    # masked-at-real edges are self-loops
    send = np.asarray(b8.senders)
    emask = np.asarray(b8.edge_mask)
    nmask = np.asarray(b8.node_mask)
    masked_real = ~emask & nmask[np.asarray(b8.receivers)]
    assert np.array_equal(send[masked_real], np.asarray(b8.receivers)[masked_real])


def test_pna_train_step_aligned_matches_plain():
    """Full PNA train steps on the two layouts stay loss-equivalent
    (reassociation-level differences only)."""
    config = flagship_config(32, 3, 16)
    samples = deterministic_graph_data(number_configurations=40, seed=0)
    train, val, test, _, _ = prepare_dataset(samples, config)
    config = update_config(config, train, val, test)
    tx = select_optimizer(config["NeuralNetwork"]["Training"])

    losses = {}
    model = state0 = None
    for tag, ra in (("plain", False), ("aligned", 8)):
        loader = GraphLoader(
            train, 16, shuffle=False, drop_last=True, dense_slots=None, run_align=ra
        )
        b = next(iter(loader))
        if model is None:
            model, variables = create_model_config(config["NeuralNetwork"], b)
            state0 = create_train_state(variables, tx)
        step = make_train_step(model, tx)
        st = jax.tree_util.tree_map(jnp.copy, state0)
        ls = []
        for _ in range(3):
            st, loss, _ = step(st, b)
            ls.append(float(loss))
        losses[tag] = ls
    np.testing.assert_allclose(losses["plain"], losses["aligned"], rtol=2e-4)


def test_local_window_kernels_interpret():
    """gather_rows_local / segment_sum_local vs plain indexing / XLA
    segment_sum, interpret mode (the real-chip gate lives in
    tpu_selfcheck)."""
    os.environ["HYDRAGNN_PALLAS"] = "interpret"
    os.environ["HYDRAGNN_LOCAL_MIN_ROWS"] = "0"
    try:
        from hydragnn_tpu.graph.segment import gather_rows_local
        from hydragnn_tpu.ops.segment_pallas import segment_sum_local_pallas

        rng = np.random.default_rng(1)
        N, E, H = 1024, 4000, 128
        g_of = np.sort(rng.integers(0, 16, E))
        senders = (g_of * 64 + rng.integers(0, 64, E)).astype(np.int32)
        perm = np.argsort(senders, kind="stable").astype(np.int32)
        win = jnp.asarray(_block_windows(senders, perm, N))
        x = jnp.asarray(rng.standard_normal((N, H)).astype(np.float32))
        s = jnp.asarray(senders)
        ct = jnp.asarray(rng.standard_normal((E, H)).astype(np.float32))

        out = gather_rows_local(x, s, win, N)
        np.testing.assert_array_equal(np.asarray(out), np.asarray(x)[senders])

        grad = jax.grad(lambda x: (gather_rows_local(x, s, win, N) * ct).sum())(x)
        ref = jax.grad(lambda x: (x[s] * ct).sum())(x)
        np.testing.assert_allclose(np.asarray(grad), np.asarray(ref), atol=2e-5)

        ssum = segment_sum_local_pallas(ct, s, win, N, interpret=True)
        sref = jax.ops.segment_sum(ct, s, N)
        np.testing.assert_allclose(np.asarray(ssum), np.asarray(sref), atol=2e-5)
    finally:
        os.environ.pop("HYDRAGNN_PALLAS", None)
        os.environ.pop("HYDRAGNN_LOCAL_MIN_ROWS", None)


def test_loader_auto_run_align_and_pad_plan():
    """AUTO: run_align engages when the dense map is off, pad plan is a
    K multiple covering aligned worst case; explicit conflict raises."""
    gs = _random_graphs(12, seed=3)
    from hydragnn_tpu.data.dataset import GraphSample

    samples = [
        GraphSample(
            x=g["x"].astype(np.float32),
            edge_index=np.stack([g["senders"], g["receivers"]]).astype(np.int32),
            graph_targets={"e": g["graph_targets"]["e"].astype(np.float32)},
        )
        for g in gs
    ]
    loader = GraphLoader(samples, 4, dense_slots=None, run_align=True)
    assert loader.run_align == 8
    assert loader.pad_edges % 8 == 0
    b = next(iter(loader))
    assert b.run_align == 8
    b.check_invariants()
    with pytest.raises(ValueError):
        GraphLoader(samples, 4, dense_slots=4, run_align=8)


def test_device_stack_stacking_with_windows_and_partial_batch():
    """Window shapes must be identical across sub-batches of one loader
    (loader-static block target) — including the all-padding filler of
    a partial final batch — or tree_map(np.stack) would raise
    (r04 review finding)."""
    from hydragnn_tpu.data.dataset import GraphSample

    rng = np.random.default_rng(5)
    samples = []
    for i in range(10):  # heterogeneous sizes: 4..40 nodes
        n = int(rng.integers(4, 41))
        s = np.arange(n)
        r = (s + 1) % n
        samples.append(
            GraphSample(
                x=rng.standard_normal((n, 3)).astype(np.float32),
                edge_index=np.stack([s, r]).astype(np.int32),
                graph_targets={"e": rng.standard_normal(1).astype(np.float32)},
            )
        )
    # batch_size 8 over 10 samples with device_stack 2 -> the last
    # batch is partial and exercises the _mask_out filler path
    loader = GraphLoader(samples, 8, device_stack=2, dense_slots=None)
    batches = list(loader)
    assert len(batches) == 2
    for b in batches:
        # stacked windows: [D=2 devices, 2 (lo/hi), n_blocks]
        assert b.sender_win.ndim == 3
        assert b.sender_win.shape[:2] == (2, 2)
