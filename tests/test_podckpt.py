"""Pod-scale fault tolerance (hydragnn_tpu/resilience/podckpt.py +
PodSupervisor): sharded checkpoints with a generation commit protocol,
heartbeat-based lost-host detection, coordinated preemption, elastic
restore, and the pod-level exit classification the supervisor restarts
from (docs/RESILIENCE.md "Pod recovery"). All CPU; the crash-mid-commit
end-to-end runs real subprocesses and is slow-marked."""

import glob
import json
import os
import subprocess
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from hydragnn_tpu.resilience import podckpt
from hydragnn_tpu.resilience.podckpt import (
    PodShardError,
    PodSignaler,
    commit_generation,
    list_committed_generations,
    pod_barrier,
    read_commit,
    restore_pod_checkpoint,
    save_pod_shard,
)
from hydragnn_tpu.resilience.preempt import PodHostLost, PreemptionHandler
from hydragnn_tpu.resilience.supervisor import (
    PodSupervisor,
    SupervisorPolicy,
    classify_pod_exit,
)
from hydragnn_tpu.utils.checkpoint import CheckpointFormatError

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _fake_state(step, value):
    from hydragnn_tpu.train.state import TrainState

    return TrainState(
        step=jnp.asarray(step, jnp.int32),
        params={
            "w": jnp.full((6, 3), float(value)),
            "b": jnp.full((4,), float(value) * 2.0),
        },
        batch_stats={"mean": jnp.full((3,), float(value) / 2.0)},
        opt_state=(),
        rng=jax.random.PRNGKey(0),
    )


def _save_generation(run_dir, state, gen, hosts=2, step=None):
    """Every simulated host writes its shard, then rank 0 commits."""
    for h in range(hosts):
        save_pod_shard(
            state, run_dir, gen=gen, host=h, hosts=hosts,
            step=step if step is not None else int(state.step),
        )
    return commit_generation(run_dir, gen, hosts, timeout_s=5.0)


def _assert_states_equal(a, b):
    for la, lb in zip(
        jax.tree_util.tree_leaves(jax.device_get(a)),
        jax.tree_util.tree_leaves(jax.device_get(b)),
    ):
        np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))


# ---------------------------------------------------------------------------
# commit protocol + elastic restore


def pytest_pod_roundtrip_and_elastic_restore(tmp_path):
    run_dir = str(tmp_path)
    state = _fake_state(7, 3.5)
    commit = _save_generation(run_dir, state, gen=1, hosts=2)
    assert commit["committed"] and commit["gen"] == 1
    assert list_committed_generations(run_dir) == [1]
    # the COMMIT record inherits step from host 0's manifest
    assert read_commit(run_dir, 1)["step"] == 7

    # restore into a DIFFERENT (single-host) process: the leaves are
    # reassembled from both hosts' shards and placed on this topology —
    # the 2-host -> 1-host elastic leg
    restored, info = restore_pod_checkpoint(_fake_state(0, 0.0), run_dir)
    assert info is not None and info["gen"] == 1 and info["hosts"] == 2
    assert info["fallbacks"] == []
    assert int(restored.step) == 7
    _assert_states_equal(restored, state)
    # the lineage latch hands the info to the train loop exactly once
    assert podckpt.consume_last_restore_info() == info
    assert podckpt.consume_last_restore_info() is None


def pytest_newest_commit_wins_and_prune_keeps_last(tmp_path):
    run_dir = str(tmp_path)
    for gen in (1, 2, 3, 4):
        assert _save_generation(
            run_dir, _fake_state(gen, float(gen)), gen=gen
        )["committed"]
    restored, info = restore_pod_checkpoint(_fake_state(0, 0.0), run_dir)
    assert info["gen"] == 4 and int(restored.step) == 4
    podckpt.prune_generations(run_dir, keep_last=2)
    assert list_committed_generations(run_dir) == [3, 4]


def pytest_torn_sidecar_falls_back_a_generation(tmp_path):
    run_dir = str(tmp_path)
    good = _fake_state(1, 1.0)
    assert _save_generation(run_dir, good, gen=1)["committed"]
    assert _save_generation(run_dir, _fake_state(2, 2.0), gen=2)["committed"]
    # corrupt gen 2's host-1 shard AFTER commit (bit rot / torn disk)
    shard = os.path.join(run_dir, "podckpt", "ckpt.gen2.host1.mp")
    with open(shard, "rb") as f:
        data = f.read()
    with open(shard, "wb") as f:
        f.write(data[: len(data) // 2])
    # restore falls back to gen 1 LOUDLY, naming the bad shard
    with pytest.warns(RuntimeWarning, match="gen2"):
        restored, info = restore_pod_checkpoint(_fake_state(0, 0.0), run_dir)
    assert info["gen"] == 1
    assert info["fallbacks"] and "2" in str(info["fallbacks"][0]["gen"])
    _assert_states_equal(restored, good)


def pytest_missing_commit_marker_is_never_valid(tmp_path):
    run_dir = str(tmp_path)
    assert _save_generation(run_dir, _fake_state(1, 1.0), gen=1)["committed"]
    # gen 2: every shard + manifest present, but the process died before
    # rank 0 wrote the COMMIT marker — the generation does not exist
    state2 = _fake_state(2, 2.0)
    for h in range(2):
        save_pod_shard(state2, run_dir, gen=2, host=h, hosts=2)
    assert list_committed_generations(run_dir) == [1]
    restored, info = restore_pod_checkpoint(_fake_state(0, 0.0), run_dir)
    assert info["gen"] == 1 and int(restored.step) == 1


def pytest_commit_bounded_wait_timeout_and_lost(tmp_path, monkeypatch):
    run_dir = str(tmp_path)
    state = _fake_state(3, 1.0)
    # only host 0 of 2 wrote its shard: bounded wait, then a recorded
    # non-commit — never a hang, never an exception
    save_pod_shard(state, run_dir, gen=1, host=0, hosts=2)
    commit = commit_generation(run_dir, 1, 2, timeout_s=0.3, poll_s=0.02)
    assert not commit["committed"] and commit.get("timeout")
    assert commit["missing"] == [1]
    assert list_committed_generations(run_dir) == []

    # with a signaler that has declared host 1 lost, the wait bails out
    # early and reports WHO was lost
    monkeypatch.setenv("HYDRAGNN_POD_LOST_AFTER_S", "0.05")
    sig = PodSignaler(run_dir, host=0, hosts=2)
    time.sleep(0.15)  # host 1 never beats after sig's birth
    commit = commit_generation(
        run_dir, 1, 2, timeout_s=5.0, poll_s=0.02, signaler=sig
    )
    assert not commit["committed"] and commit["lost"] == [1]


def pytest_pod_barrier_bounded_wait(tmp_path):
    run_dir = str(tmp_path)
    ok, missing = pod_barrier(run_dir, "setup", 0, 2, timeout_s=0.3, poll_s=0.02)
    assert not ok and missing == [1]
    # once the peer arrives the same barrier completes
    ok, missing = pod_barrier(run_dir, "setup", 1, 2, timeout_s=2.0, poll_s=0.02)
    assert ok and missing == []


# ---------------------------------------------------------------------------
# heartbeats, lost detection, coordinated preemption


def pytest_signaler_lost_detection_dedupe_and_stale_beats(tmp_path, monkeypatch):
    run_dir = str(tmp_path)
    monkeypatch.setenv("HYDRAGNN_POD_HEARTBEAT_S", "0.01")
    monkeypatch.setenv("HYDRAGNN_POD_LOST_AFTER_S", "0.2")
    # host 1 beats, then "dies"; host 0's signaler is created AFTER, so
    # the stale beat must NOT count as liveness — but host 1 still gets
    # the full threshold from host 0's birth before being declared
    sig1 = PodSignaler(run_dir, host=1, hosts=2)
    sig1.heartbeat(epoch=0, force=True)
    time.sleep(0.05)
    sig0 = PodSignaler(run_dir, host=0, hosts=2)
    assert sig0.lost_hosts() == []  # within the grace from birth
    time.sleep(0.3)
    assert sig0.lost_hosts() == [1]
    # exactly-once declaration no matter how many sites poll
    assert sig0.undeclared_lost() == [1]
    assert sig0.undeclared_lost() == []
    assert sig0.mark_declared([1]) == []
    # a fresh beat revives the peer (lost_hosts is a live view)
    sig1.heartbeat(epoch=1, force=True)
    assert sig0.lost_hosts() == []


def pytest_signaler_disarmed_by_default(tmp_path, monkeypatch):
    monkeypatch.delenv("HYDRAGNN_POD_LOST_AFTER_S", raising=False)
    sig = PodSignaler(str(tmp_path), host=0, hosts=4)
    assert sig.lost_after_s == 0.0
    assert sig.lost_hosts() == []  # sequential CI hosts are never "lost"


def pytest_coordinated_preempt_posting_and_max_gen(tmp_path):
    run_dir = str(tmp_path)
    sig0 = PodSignaler(run_dir, host=0, hosts=2)
    sig1 = PodSignaler(run_dir, host=1, hosts=2)
    # the SIGTERM handler announces through the attached signaler
    handler = PreemptionHandler(hard_exit=False)
    handler.signaler = sig1
    handler.proposed_gen = 3
    handler._handle(15, None)
    req = sig0.preempt_request()
    assert req["gen"] == 3 and req["host"] == 1 and req["signum"] == 15
    # the posting with the HIGHEST generation wins pod-wide
    sig0.post_preempt(5, signum=15)
    assert sig1.preempt_request()["gen"] == 5
    # a restarted host clears ITS OWN stale posting at init
    PodSignaler(run_dir, host=0, hosts=2)
    assert sig1.preempt_request()["gen"] == 3  # host 1's survives


def pytest_pod_injection_spec_parsers(monkeypatch):
    from hydragnn_tpu.resilience.inject import (
        maybe_pod_lost_heartbeat,
        maybe_pod_torn_shard,
    )

    monkeypatch.setenv("HYDRAGNN_INJECT_POD_TORN_SHARD", "1:2")
    assert maybe_pod_torn_shard(1, 2)
    assert not maybe_pod_torn_shard(0, 2)
    assert not maybe_pod_torn_shard(1, 1)
    monkeypatch.setenv("HYDRAGNN_INJECT_POD_LOST_HEARTBEAT", "1:3")
    assert maybe_pod_lost_heartbeat(1, 3)
    assert maybe_pod_lost_heartbeat(1, 5)  # epoch >= E stays silent
    assert not maybe_pod_lost_heartbeat(1, 2)
    assert not maybe_pod_lost_heartbeat(0, 3)
    assert not maybe_pod_lost_heartbeat(1, None)


# ---------------------------------------------------------------------------
# checkpoint format versioning (satellite: forward-compat refusal)


def pytest_format_version_stamped_and_future_rejected(tmp_path):
    from hydragnn_tpu.utils.checkpoint import (
        CHECKPOINT_FORMAT_VERSION,
        load_existing_model,
        load_train_meta,
        save_model,
        save_train_meta,
    )

    log_dir = str(tmp_path)
    save_model(_fake_state(1, 1.0), "run", log_dir)
    save_train_meta({"epoch": 1, "step": 1}, "run", log_dir)
    meta = load_train_meta("run", log_dir)
    assert meta["format_version"] == CHECKPOINT_FORMAT_VERSION

    # legacy (pre-versioning) sidecar: no stamp, accepted unchanged
    meta_path = os.path.join(log_dir, "run", "run.meta.json")
    legacy = dict(meta)
    legacy.pop("format_version")
    with open(meta_path, "w") as f:
        json.dump(legacy, f)
    restored = load_existing_model(_fake_state(0, 0.0), "run", log_dir)
    assert int(restored.step) == 1

    # a FUTURE format refuses loudly with the typed error (the restart
    # supervisor fail-fasts on it instead of retrying)
    future = dict(legacy, format_version=CHECKPOINT_FORMAT_VERSION + 1)
    with open(meta_path, "w") as f:
        json.dump(future, f)
    with pytest.raises(CheckpointFormatError):
        load_existing_model(_fake_state(0, 0.0), "run", log_dir)


def pytest_future_commit_record_rejected(tmp_path):
    run_dir = str(tmp_path)
    assert _save_generation(run_dir, _fake_state(1, 1.0), gen=1)["committed"]
    commit_path = os.path.join(run_dir, "podckpt", "gen1.COMMIT")
    with open(commit_path) as f:
        rec = json.load(f)
    rec["format_version"] = rec["format_version"] + 1
    with open(commit_path, "w") as f:
        json.dump(rec, f)
    with pytest.raises(CheckpointFormatError):
        read_commit(run_dir, 1)
    # the refusal PROPAGATES out of restore — an upgrade refusal must
    # never silently fall back to an older generation
    with pytest.raises(CheckpointFormatError):
        restore_pod_checkpoint(_fake_state(0, 0.0), run_dir)


# ---------------------------------------------------------------------------
# pod-level exit classification + PodSupervisor policy (fake processes)


def pytest_classify_pod_exit_contract():
    assert classify_pod_exit({0: 0, 1: 0}) == "completed"
    assert classify_pod_exit({0: 75, 1: -9}) == "host_lost"  # signal death wins
    assert classify_pod_exit({0: 0, 1: -15}) == "host_lost"
    assert classify_pod_exit({0: 75, 1: 0}) == "preempted"
    assert classify_pod_exit({0: 79, 1: 75}) == "preempted"
    assert classify_pod_exit({0: 79, 1: 0}) == "hung"
    assert classify_pod_exit({0: 1, 1: 0}) == "crash"
    # fail-fast beats everything, including a lost host
    assert classify_pod_exit({0: 78, 1: -9}) == "config_error"
    assert classify_pod_exit({0: 76, 1: 75}) == "rollback_exhausted"
    with pytest.raises(ValueError):
        classify_pod_exit({})


class _FakeProc:
    """Scripted child: ``rc=None`` means still running; terminate()
    resolves to ``on_terminate`` (a graceful generation cut -> 75)."""

    def __init__(self, rc=None, on_terminate=75):
        self.rc = rc
        self.on_terminate = on_terminate

    def poll(self):
        return self.rc

    def terminate(self):
        if self.rc is None:
            self.rc = self.on_terminate

    def kill(self):
        self.rc = -9

    def wait(self, timeout=None):
        if self.rc is None:
            raise subprocess.TimeoutExpired("cmd", timeout)
        return self.rc


def pytest_pod_supervisor_host_lost_restarts_promptly(tmp_path):
    from hydragnn_tpu.obs.flight import FlightRecorder, read_flight_record

    # attempt 0: host 1 SIGKILLed mid-run, host 0 still alive (it gets
    # SIGTERMed and cuts a generation -> 75); attempt 1: both complete
    script = [[_FakeProc(rc=None), _FakeProc(rc=-9)],
              [_FakeProc(rc=0), _FakeProc(rc=0)]]
    launches = []

    def fake_popen(argv, env=None):
        attempt = len(launches) // 2
        host = len(launches) % 2
        launches.append({"argv": list(argv), "env": dict(env or {})})
        return script[attempt][host]

    delays = []
    path = str(tmp_path / "flight.jsonl")
    with FlightRecorder(path) as fl:
        fl.start_run({"supervisor": True})
        sup = PodSupervisor(
            ["cmd"],
            hosts=2,
            policy=SupervisorPolicy(max_restarts=0),  # loss is NOT a crash
            env={"HYDRAGNN_INJECT_POD_KILL_HOST": "1:2", "KEEP": "1"},
            flight=fl,
            run_id="podrun",
            popen=fake_popen,
            sleep=delays.append,
        )
        result = sup.run()
    assert result["status"] == "completed"
    assert result["preemptions"] == 1 and result["restarts"] == 0
    assert delays == []  # prompt restart, no crash backoff
    assert [h["cause"] for h in result["history"]] == ["host_lost", "completed"]
    assert result["history"][0]["exit_codes"] == {"0": 75, "1": -9}

    # per-host identity env on every child; restarted children resume
    # with the injection stripped so the fault fires exactly once
    for i, launch in enumerate(launches):
        env = launch["env"]
        assert env["HYDRAGNN_PODVIEW_HOST"] == str(i % 2)
        assert env["HYDRAGNN_PODVIEW_HOSTS"] == "2"
        assert env["HYDRAGNN_PODVIEW_RUN_ID"] == "podrun"
        assert env["KEEP"] == "1"
    assert "HYDRAGNN_INJECT_POD_KILL_HOST" in launches[0]["env"]
    for launch in launches[2:]:
        assert "HYDRAGNN_INJECT_POD_KILL_HOST" not in launch["env"]
        assert launch["env"]["HYDRAGNN_AUTO_RESUME"] == "1"

    events = read_flight_record(path)
    (lost,) = [e for e in events if e.get("kind") == "host_lost"]
    assert lost["host"] == 1 and lost["exit_code"] == -9
    (restart,) = [e for e in events if e.get("kind") == "restart"]
    assert restart["cause"] == "host_lost" and restart["delay_s"] == 0.0


def pytest_pod_supervisor_elastic_drops_a_host():
    script = [[_FakeProc(rc=None), _FakeProc(rc=None), _FakeProc(rc=-9)],
              [_FakeProc(rc=0), _FakeProc(rc=0)]]
    launches = []

    def fake_popen(argv, env=None):
        procs = script[0] if len(launches) < 3 else script[1]
        proc = procs[len(launches) if len(launches) < 3 else len(launches) - 3]
        launches.append(dict(env or {}))
        return proc

    sup = PodSupervisor(
        ["cmd"], hosts=3, env={}, popen=fake_popen,
        sleep=lambda s: None, elastic=True,
    )
    result = sup.run()
    assert result["status"] == "completed"
    assert result["hosts"] == 2  # restarted at N-1 after the loss
    assert [h["hosts"] for h in result["history"]] == [3, 2]
    assert launches[3]["HYDRAGNN_PODVIEW_HOSTS"] == "2"
    assert len(launches) == 5


def pytest_pod_supervisor_fail_fast_kills_peers():
    # one host exits 78: the pod fail-fasts — no restart, peers stopped
    procs = [_FakeProc(rc=None), _FakeProc(rc=78)]
    sup = PodSupervisor(
        ["cmd"], hosts=2, env={},
        popen=lambda argv, env=None: procs.pop(0),
        sleep=lambda s: None,
    )
    result = sup.run()
    assert result["status"] == "failed_fast"
    assert result["cause"] == "config_error"
    assert result["attempts"] == 1


# ---------------------------------------------------------------------------
# crash-mid-commit end to end (real subprocesses)

_CHILD = r"""
import sys
sys.path.insert(0, {repo!r})
from __graft_entry__ import _load_platform_module
_load_platform_module().pin_virtual_cpu_mesh(1)

from hydragnn_tpu.resilience import run_guard
from hydragnn_tpu.api import run_training
from hydragnn_tpu.data.synthetic import deterministic_graph_data
from hydragnn_tpu.flagship import flagship_config

cfg = flagship_config(hidden_dim=8, num_conv_layers=2, batch_size=5, num_epoch=3)
cfg["NeuralNetwork"]["Training"].update({training!r})
samples = deterministic_graph_data(
    number_configurations=20, unit_cell_x_range=(2, 3), unit_cell_y_range=(2, 3),
    unit_cell_z_range=(2, 3), seed=0)
with run_guard():
    run_training(cfg, samples=samples, log_dir=sys.argv[1] + "/logs/")
print("CHILD-COMPLETED")
"""


def _run_pod_host(tmp_path, host, hosts, env_extra, timeout=240):
    script = tmp_path / "child.py"
    script.write_text(
        _CHILD.format(repo=_REPO, training={"checkpoint_every": 1})
    )
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        HYDRAGNN_PODVIEW_HOST=str(host),
        HYDRAGNN_PODVIEW_HOSTS=str(hosts),
        HYDRAGNN_PODVIEW_RUN_ID="podgen",
        **env_extra,
    )
    env.pop("XLA_FLAGS", None)
    return subprocess.run(
        [sys.executable, str(script), str(tmp_path)],
        env=env,
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        text=True,
        timeout=timeout,
    )


@pytest.mark.slow
def pytest_crash_mid_commit_leaves_only_committed_generations(tmp_path):
    # host 1 is SIGKILLed INSIDE its gen-2 shard write (after the shard
    # + sidecar, before the manifest — the worst torn point); host 0
    # then runs all 3 epochs, its gen-1 commit succeeds, gens 2..3 fail
    # the bounded wait and are recorded, never committed
    proc = _run_pod_host(
        tmp_path, host=1, hosts=2,
        env_extra={"HYDRAGNN_INJECT_POD_KILL_HOST": "1:2"},
    )
    assert proc.returncode == -9, proc.stdout
    proc = _run_pod_host(
        tmp_path, host=0, hosts=2,
        env_extra={"HYDRAGNN_POD_COMMIT_TIMEOUT_S": "1.5"},
    )
    assert proc.returncode == 0, proc.stdout

    (run_dir,) = glob.glob(str(tmp_path / "logs" / "*/"))
    run_dir = run_dir.rstrip("/")
    assert list_committed_generations(run_dir) == [1]
    # the torn gen-2 has host 1's shard but no manifest and no COMMIT
    assert os.path.exists(
        os.path.join(run_dir, "podckpt", "ckpt.gen2.host1.mp")
    )
    assert not os.path.exists(
        os.path.join(run_dir, "podckpt", "ckpt.gen2.host1.manifest.json")
    )
    # host 0's flight carries the PodCommitFailed evidence
    from hydragnn_tpu.obs.flight import read_flight_record

    events = read_flight_record(os.path.join(run_dir, "flight.jsonl"))
    fails = [
        e for e in events
        if e.get("kind") == "error" and e.get("error_type") == "PodCommitFailed"
    ]
    # gen 2 once, gen 3 twice (the cadence write and the final post-
    # recalibration write both cut gen 3) — all recorded, none committed
    assert len(fails) == 3
    assert {
        int(str(e["error"]).split("generation ")[1].split(" ")[0]) for e in fails
    } == {2, 3}
    # a restart would rise from the only committed generation
    commit = podckpt.latest_commit_info(run_dir)
    assert commit["gen"] == 1 and commit["hosts"] == 2
