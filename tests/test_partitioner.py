"""Unified Partitioner tests (hydragnn_tpu/parallel/partitioner.py) on
the forced 8-device CPU host mesh (conftest pins
``--xla_force_host_platform_device_count=8``): mesh composition with
size-1 auto-collapse, FSDP parameter+optimizer sharding that bit-matches
the replicated data-parallel reference, per-device memory accounting,
the replicated-leaf loudness contract, serve warmup under a partitioner
mesh with zero post-warmup compile misses, and the scan-eligibility
"partitioner says single-device" path. docs/PARALLELISM.md is the prose
companion of these contracts.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest
from jax.sharding import PartitionSpec as P

from hydragnn_tpu.data.ingest import prepare_dataset
from hydragnn_tpu.data.loader import GraphLoader
from hydragnn_tpu.data.synthetic import deterministic_graph_data
from hydragnn_tpu.models.create import create_model_config
from hydragnn_tpu.parallel import FSDP_AXIS, ParallelConfig, Partitioner
from hydragnn_tpu.train import create_train_state, select_optimizer
from hydragnn_tpu.utils.config import update_config

from test_data_pipeline import base_config

D = 8  # virtual devices from conftest


def _is_fsdp_sharded(leaf) -> bool:
    spec = leaf.sharding.spec
    return any(
        e == FSDP_AXIS or (isinstance(e, tuple) and FSDP_AXIS in e)
        for e in spec
        if e is not None
    )


def _shardable(leaf, fsdp: int) -> bool:
    return any(d > 0 and d % fsdp == 0 for d in leaf.shape)


@pytest.fixture(scope="module")
def problem():
    cfg = base_config(multihead=True)
    arch = cfg["NeuralNetwork"]["Architecture"]
    arch["model_type"] = "GIN"
    # fsdp-friendly widths: hidden/head dims divisible by the test's
    # fsdp factors so the sharding coverage (and the >=3x per-device
    # byte drop) is dominated by shardable leaves, like a real config
    arch["hidden_dim"] = 16
    arch["output_heads"]["graph"]["dim_sharedlayers"] = 8
    arch["output_heads"]["graph"]["dim_headlayers"] = [16, 16]
    arch["output_heads"]["node"]["dim_headlayers"] = [8, 8]
    cfg["NeuralNetwork"]["Training"]["batch_size"] = 16
    samples = deterministic_graph_data(number_configurations=64, seed=7)
    train, val, test, _, _ = prepare_dataset(samples, cfg)
    cfg = update_config(cfg, train, val, test)
    loader = GraphLoader(train, 16, shuffle=False, device_stack=D, drop_last=True)
    example = jax.tree_util.tree_map(lambda x: x[0], next(iter(loader)))
    model, variables = create_model_config(cfg["NeuralNetwork"], example)
    return cfg, model, variables, loader


# ---------------------------------------------------------------------------
# mesh composition
# ---------------------------------------------------------------------------


def pytest_mesh_composition_and_auto_collapse():
    p = Partitioner(data=8)
    assert p.axis_names == ("data",)
    assert dict(p.mesh.shape) == {"data": 8}
    assert p.batch_sharding().spec == P("data")
    assert not p.single_device and p.device_stack == 8

    p = Partitioner(data=2, fsdp=4)
    assert p.axis_names == ("data", "fsdp")
    assert p.lead_spec == ("data", "fsdp")
    assert p.fsdp_factor == 4 and p.device_stack == 8

    # size-1 axes collapse out of the mesh entirely
    p = Partitioner(fsdp=8)
    assert p.axis_names == ("fsdp",) and p.lead_spec == "fsdp"
    p = Partitioner(data=2, fsdp=2, edge=2)
    assert p.axis_names == ("data", "fsdp", "edge")

    # the degenerate config is the single-device story
    p = Partitioner()
    assert p.single_device and p.mesh is None and p.device_stack == 1
    assert p.batch_sharding() is None

    with pytest.raises(ValueError):
        ParallelConfig(data=0)
    with pytest.raises(ValueError):
        Partitioner(data=16)  # more devices than the host mesh has


def pytest_from_config_knobs():
    nn = {"Parallel": {"fsdp": 2}, "Training": {"Optimizer": {}}}
    p = Partitioner.from_config(nn, device_stack=8)
    assert p.config.data == 4 and p.config.fsdp == 2

    # fsdp must divide the batch device axis
    with pytest.raises(ValueError):
        Partitioner.from_config(
            {"Parallel": {"fsdp": 3}, "Training": {}}, device_stack=8
        )

    # ZeRO-1 is subsumed by (and ignored under) fsdp > 1
    nn = {
        "Parallel": {"fsdp": 2},
        "Training": {"Optimizer": {"use_zero_redundancy": True}},
    }
    assert Partitioner.from_config(nn, device_stack=8).config.zero1 is False
    nn = {"Training": {"Optimizer": {"use_zero_redundancy": True}}}
    assert Partitioner.from_config(nn, device_stack=8).config.zero1 is True


# ---------------------------------------------------------------------------
# FSDP training: parity with replicated DP + committed shardings
# ---------------------------------------------------------------------------


def pytest_fsdp_train_matches_replicated_dp(problem):
    """fsdp=2 and fsdp=4 train steps match the replicated data=8
    reference (same devices, same pmean — only the state layout and
    collective reduction order differ, hence the tolerance), and every
    shardable parameter AND optimizer leaf is committed-sharded over the
    fsdp axis (asserted from the NamedShardings, not inferred)."""
    cfg, model, variables, loader = problem
    tx = select_optimizer({"Optimizer": {"type": "AdamW", "learning_rate": 0.01}})
    batches = list(loader)[:3]

    ref = Partitioner(data=D)
    state_ref = ref.shard_init(create_train_state(variables, tx, seed=0))
    step_ref = ref.shard_train_step(model, tx)
    ref_losses = []
    for b in batches:
        state_ref, loss, _ = step_ref(state_ref, b)
        ref_losses.append(float(loss))
    ref_params = jax.device_get(state_ref.params)

    for fsdp in (2, 4):
        part = Partitioner(data=D // fsdp, fsdp=fsdp)
        state = part.shard_init(create_train_state(variables, tx, seed=0))
        man = part.manifest(state=state)
        reported = set(man["replicated_leaves"])
        # committed shardings: every shardable leaf carries the fsdp
        # axis; the rest are accounted for in replicated_leaves
        for section, tree in (
            ("params", state.params),
            ("opt_state", state.opt_state),
        ):
            flat = jax.tree_util.tree_leaves_with_path(tree)
            for path, leaf in flat:
                if not hasattr(leaf, "sharding") or leaf.ndim == 0:
                    continue
                if _shardable(leaf, fsdp):
                    assert _is_fsdp_sharded(leaf), (
                        fsdp,
                        section,
                        jax.tree_util.keystr(path),
                        leaf.shape,
                    )
                elif int(np.prod(leaf.shape)) > 1:
                    assert section + jax.tree_util.keystr(path) in reported

        step = part.shard_train_step(model, tx)
        losses = []
        for b in batches:
            state, loss, _ = step(state, b)
            losses.append(float(loss))
        # documented reduction-order tolerance (hierarchical psum over
        # (data, fsdp) vs flat psum over data)
        np.testing.assert_allclose(losses, ref_losses, rtol=2e-5)
        for a, b in zip(
            jax.tree_util.tree_leaves(ref_params),
            jax.tree_util.tree_leaves(jax.device_get(state.params)),
        ):
            np.testing.assert_allclose(a, b, rtol=2e-4, atol=2e-5)
        # the updated state keeps the committed fsdp layout (no silent
        # re-replication across donated steps)
        n_sharded = sum(
            _is_fsdp_sharded(l)
            for l in jax.tree_util.tree_leaves(state.params)
            if hasattr(l, "sharding")
        )
        assert n_sharded == man["params"]["sharded"] > 0


def pytest_fsdp_memory_drop_at_least_3x(problem):
    """The acceptance criterion: fsdp=4 drops per-device param+optimizer
    bytes >=3x vs the replicated layout, as reported by the same
    manifest block the flight record carries."""
    cfg, model, variables, loader = problem
    tx = select_optimizer({"Optimizer": {"type": "AdamW", "learning_rate": 0.01}})
    state = create_train_state(variables, tx)

    rep = Partitioner(data=D).manifest(state=state)
    rep_dev = rep["params"]["bytes_per_device"] + rep["opt"]["bytes_per_device"]
    assert rep_dev == rep["params"]["bytes_global"] + rep["opt"]["bytes_global"]

    part = Partitioner(data=2, fsdp=4)
    man = part.manifest(state=state)
    f_dev = man["params"]["bytes_per_device"] + man["opt"]["bytes_per_device"]
    assert f_dev * 3 <= rep_dev, (f_dev, rep_dev)
    assert man["params"]["sharded"] > 0 and man["opt"]["sharded"] > 0


def pytest_fsdp_eval_and_stats_parity(problem):
    cfg, model, variables, loader = problem
    tx = select_optimizer({"Optimizer": {"type": "AdamW", "learning_rate": 0.01}})
    batch = next(iter(loader))

    ref = Partitioner(data=D)
    state_ref = ref.shard_init(create_train_state(variables, tx, seed=0))
    loss_ref, tasks_ref = ref.shard_eval_step(model)(state_ref, batch)

    part = Partitioner(data=2, fsdp=4)
    state = part.shard_init(create_train_state(variables, tx, seed=0))
    loss, tasks = part.shard_eval_step(model)(state, batch)
    np.testing.assert_allclose(float(loss), float(loss_ref), rtol=1e-5)
    np.testing.assert_allclose(
        np.asarray(tasks), np.asarray(tasks_ref), rtol=1e-5
    )

    # with_outputs keeps the device-concatenated contract test_epoch needs
    loss2, _, outputs = part.shard_eval_step(model, with_outputs=True)(
        state, batch
    )
    assert np.asarray(outputs[0]).shape[0] == batch.graph_mask.shape[0] * (
        batch.graph_mask.shape[1]
    )

    # BN recalibration runs and stays finite under the fsdp layout
    state = part.shard_stats_step(model)(state, batch)
    for leaf in jax.tree_util.tree_leaves(state.batch_stats):
        assert np.isfinite(np.asarray(leaf)).all()


# ---------------------------------------------------------------------------
# replicated-leaf loudness (the ZeRO-1 silent-replication fix)
# ---------------------------------------------------------------------------


def pytest_replicated_leaves_warn_with_paths():
    from hydragnn_tpu.train.state import TrainState

    state = TrainState(
        step=jnp.zeros((), jnp.int32),
        params={"w": jnp.zeros((8, 8)), "odd": jnp.zeros((3, 5))},
        batch_stats={},
        opt_state={"mu": {"w": jnp.zeros((8, 8)), "odd": jnp.zeros((3, 5))}},
        rng=jax.random.PRNGKey(0),
    )
    part = Partitioner(data=2, fsdp=4)
    with pytest.warns(RuntimeWarning, match="REPLICATED"):
        placed = part.shard_init(state)
    man = part.manifest(state=state)
    assert "params['odd']" in man["replicated_leaves"]
    assert "opt_state['mu']['odd']" in man["replicated_leaves"]
    assert _is_fsdp_sharded(placed.params["w"])
    assert not _is_fsdp_sharded(placed.params["odd"])
    # the warning is once-per-partitioner, not once-per-placement
    import warnings as _w

    with _w.catch_warnings():
        _w.simplefilter("error")
        part.shard_init(state)


def pytest_zero1_replication_warns_with_paths(problem):
    """The legacy ZeRO-1 path inherits the loudness contract: a
    non-divisible first axis logs one rank-0 warning naming the leaf."""
    import hydragnn_tpu.parallel.sharded as sharded_mod

    cfg, model, variables, loader = problem
    tx = select_optimizer({"Optimizer": {"type": "AdamW", "learning_rate": 0.01}})
    state = create_train_state(variables, tx)
    part = Partitioner(data=D, zero1=True)
    with pytest.warns(RuntimeWarning, match="REPLICATED"):
        part.shard_init(state)
    man = part.manifest(state=state)
    # every reported path names an optimizer leaf
    assert man["replicated_leaves"]
    assert all(p.startswith("opt_state") for p in man["replicated_leaves"])

    # the legacy entry point (place_state(zero1=True)) warns too
    from hydragnn_tpu.parallel import place_state

    sharded_mod._warned_zero1_replicated = False
    with pytest.warns(RuntimeWarning, match="ZeRO-1.*REPLICATED"):
        place_state(part.mesh, state, zero1=True)
    sharded_mod._warned_zero1_replicated = False


# ---------------------------------------------------------------------------
# composed edge axis
# ---------------------------------------------------------------------------


def pytest_edge_composed_mesh_smoke():
    cfg = base_config(multihead=False)
    cfg["NeuralNetwork"]["Architecture"]["model_type"] = "GIN"
    cfg["NeuralNetwork"]["Training"]["batch_size"] = 8
    samples = deterministic_graph_data(number_configurations=16, seed=3)
    train, _, _, _, _ = prepare_dataset(samples, cfg)
    cfg = update_config(cfg, train, train, train)
    d_data, d_edge = 2, 2
    loader = GraphLoader(
        train, 8, shuffle=False, device_stack=d_data, edge_multiple=d_edge * 8
    )
    example = jax.tree_util.tree_map(lambda x: x[0], next(iter(loader)))
    model, variables = create_model_config(cfg["NeuralNetwork"], example)
    tx = select_optimizer({"Optimizer": {"type": "SGD", "learning_rate": 0.05}})

    part = Partitioner(data=d_data, edge=d_edge)
    part.attach_loader(loader)  # per-field placer: edge leaves split too
    state = part.shard_init(create_train_state(variables, tx, seed=0))
    step = part.shard_train_step(model, tx)
    batch = next(iter(loader))
    assert batch.senders.sharding.spec == P("data", "edge")
    state, loss, _ = step(state, batch)
    assert np.isfinite(float(loss))
    loss_e, tasks_e = part.shard_eval_step(model)(state, batch)
    assert np.isfinite(float(loss_e))
    state = part.shard_stats_step(model)(state, batch)
    for leaf in jax.tree_util.tree_leaves(state.batch_stats):
        assert np.isfinite(np.asarray(leaf)).all()


# ---------------------------------------------------------------------------
# serve warmup under a partitioner mesh
# ---------------------------------------------------------------------------


def pytest_serve_warmup_under_partitioner_mesh():
    """The bucket ladder AOT-compiles under the partitioner's mesh with
    fsdp-sharded served variables; traffic then runs with 0 post-warmup
    compile misses and answers matching the single-device server."""
    from hydragnn_tpu.flagship import build_flagship
    from hydragnn_tpu.serve import ModelRegistry, ModelServer, ServeConfig

    _, model, variables, loader = build_flagship(
        n_samples=24, hidden_dim=8, num_conv_layers=2, batch_size=4,
        unit_cells=(2, 3),
    )
    samples = list(loader.all_samples)
    registry = ModelRegistry()

    served_1dev = registry.register("plain", model, variables)
    part = Partitioner(fsdp=2)
    served_fsdp = registry.register(
        "fsdp", model, variables, partitioner=part
    )
    assert any(
        _is_fsdp_sharded(l)
        for l in jax.tree_util.tree_leaves(served_fsdp.variables["params"])
    )

    sc = ServeConfig(max_batch=4, num_buckets=2, max_delay_ms=2.0)
    with ModelServer(served_1dev, samples, sc) as ref_server:
        ref = ref_server.predict_many(samples[:6], timeout=120)
    with ModelServer(served_fsdp, samples, sc) as server:
        assert server.partitioner is part
        got = server.predict_many(samples[:6], timeout=120)
        snap = server.metrics_snapshot()
        assert snap["compile_misses"] == 0, snap
        # zero-downtime reload reuses the warm fsdp ladder
        server.reload(variables=dict(variables))
        got2 = server.predict(samples[0], timeout=120)
        snap = server.metrics_snapshot()
        assert snap["compile_misses"] == 0 and snap["reloads"] == 1, snap
    for a, b in zip(ref, got):
        for k in a:
            np.testing.assert_allclose(b[k], a[k], rtol=2e-5, atol=1e-6)
    for k in ref[0]:
        np.testing.assert_allclose(got2[k], ref[0][k], rtol=2e-5, atol=1e-6)


# ---------------------------------------------------------------------------
# scan-epoch eligibility: the partitioner is the topology oracle
# ---------------------------------------------------------------------------


def pytest_scan_eligibility_uses_partitioner():
    from hydragnn_tpu.train.loop import _scan_auto_eligible

    cfg = base_config(multihead=False)
    cfg["NeuralNetwork"]["Training"]["batch_size"] = 4
    samples = deterministic_graph_data(number_configurations=8, seed=1)
    train, _, _, _, _ = prepare_dataset(samples, cfg)
    loader = GraphLoader(train, 4, shuffle=False)

    ok, reason = _scan_auto_eligible(loader, partitioner=Partitioner())
    assert ok, reason
    ok, reason = _scan_auto_eligible(
        loader, partitioner=Partitioner(data=2, fsdp=4)
    )
    assert not ok and "partitioner" in reason
