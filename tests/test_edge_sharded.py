"""Edge-sharded giant-graph aggregation on the 8-device CPU mesh:
partitioned results must match the single-device reference exactly."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from hydragnn_tpu.parallel import make_mesh
from hydragnn_tpu.parallel.edge_sharded import (
    edge_sharded_aggregate,
    edge_sharded_gin_layer,
    place_edge_shards,
    shard_edges,
)

D = 8


@pytest.fixture
def giant_graph():
    rng = np.random.default_rng(0)
    n, e, h = 300, 5000, 16
    nodes = rng.normal(size=(n, h)).astype(np.float32)
    senders = rng.integers(0, n, e).astype(np.int32)
    receivers = rng.integers(0, n, e).astype(np.int32)
    return nodes, senders, receivers


def pytest_edge_sharded_sum_matches_reference(giant_graph):
    nodes, senders, receivers = giant_graph
    n = nodes.shape[0]
    mesh = make_mesh(D)
    snd, rcv, _, mask = shard_edges(senders, receivers, None, D)
    snd, rcv, mask = place_edge_shards(mesh, snd, rcv, mask)

    agg = edge_sharded_aggregate(
        mesh, lambda x_i, x_j: x_j, jnp.asarray(nodes), snd, rcv, mask
    )
    ref = jax.ops.segment_sum(nodes[senders], jnp.asarray(receivers), n)
    np.testing.assert_allclose(np.asarray(agg), np.asarray(ref), rtol=1e-5, atol=1e-5)


def pytest_edge_sharded_with_edge_data(giant_graph):
    nodes, senders, receivers = giant_graph
    n = nodes.shape[0]
    rng = np.random.default_rng(1)
    weights = rng.normal(size=(len(senders), 1)).astype(np.float32)
    mesh = make_mesh(D)
    snd, rcv, w, mask = shard_edges(senders, receivers, weights, D)
    snd, rcv, w, mask = place_edge_shards(mesh, snd, rcv, w, mask)

    agg = edge_sharded_aggregate(
        mesh,
        lambda x_i, x_j, ew: x_j * ew,
        jnp.asarray(nodes),
        snd,
        rcv,
        mask,
        edge_data=w,
    )
    ref = jax.ops.segment_sum(
        nodes[senders] * weights, jnp.asarray(receivers), n
    )
    np.testing.assert_allclose(np.asarray(agg), np.asarray(ref), rtol=1e-4, atol=1e-4)


def pytest_edge_sharded_gin_layer_jits(giant_graph):
    nodes, senders, receivers = giant_graph
    h = nodes.shape[1]
    rng = np.random.default_rng(2)
    w1 = rng.normal(size=(h, h)).astype(np.float32) * 0.1
    w2 = rng.normal(size=(h, h)).astype(np.float32) * 0.1
    b1 = np.zeros(h, np.float32)
    b2 = np.zeros(h, np.float32)
    mesh = make_mesh(D)
    snd, rcv, _, mask = shard_edges(senders, receivers, None, D)
    snd, rcv, mask = place_edge_shards(mesh, snd, rcv, mask)

    fn = jax.jit(
        lambda nd: edge_sharded_gin_layer(
            mesh, nd, snd, rcv, mask, w1, b1, w2, b2
        )
    )
    out = fn(jnp.asarray(nodes))
    assert out.shape == nodes.shape
    assert np.isfinite(np.asarray(out)).all()
    # eps-scaled self term dominates for isolated nodes: check a node with
    # no incoming edges matches the pure-MLP path
    iso = np.setdiff1d(np.arange(nodes.shape[0]), np.unique(receivers))
    if len(iso):
        i = int(iso[0])
        ref = jax.nn.relu((101.0 * nodes[i]) @ w1 + b1) @ w2 + b2
        np.testing.assert_allclose(np.asarray(out)[i], np.asarray(ref), rtol=1e-4)


def pytest_giant_graph_full_model_gspmd():
    """Full-model giant-graph parallelism via sharding annotations: a
    plain jitted train step over a batch placed with place_giant_batch
    (edge arrays sharded over the mesh, nodes replicated) must produce
    the same loss and parameter update as the unsharded step — XLA's
    SPMD pass owns the partitioning and the gradient collectives."""
    from hydragnn_tpu.graph import batch_graphs
    from hydragnn_tpu.models import ModelConfig, create_model
    from hydragnn_tpu.parallel.edge_sharded import (
        edge_axis_shardings,
        place_giant_batch,
    )
    from hydragnn_tpu.train import create_train_state, make_train_step, select_optimizer
    from jax.sharding import PartitionSpec

    rng = np.random.default_rng(1)
    n, e = 200, 4096
    senders = rng.integers(0, n, e).astype(np.int32)
    receivers = rng.integers(0, n, e).astype(np.int32)
    g = {
        "x": rng.normal(size=(n, 4)).astype(np.float32),
        "senders": senders,
        "receivers": receivers,
        "graph_targets": {"energy": np.asarray([1.5], np.float32)},
    }
    batch = batch_graphs([g], n_node_pad=n + 8, n_edge_pad=e + 2 * D, n_graph_pad=2)

    cfg = ModelConfig(
        model_type="GIN",
        input_dim=4,
        hidden_dim=16,
        output_dim=(1,),
        output_type=("graph",),
        output_names=("energy",),
        task_weights=(1.0,),
        num_conv_layers=2,
        graph_num_sharedlayers=1,
        graph_dim_sharedlayers=8,
        graph_num_headlayers=1,
        graph_dim_headlayers=(8,),
    )
    model, variables = create_model(cfg, batch)
    tx = select_optimizer({"Optimizer": {"type": "SGD", "learning_rate": 0.05}})
    step = make_train_step(model, tx)

    state_plain = create_train_state(variables, tx, seed=0)
    state_plain, loss_plain, _ = step(state_plain, batch)

    mesh = make_mesh(D)
    sh = edge_axis_shardings(mesh, batch)
    # edge-axis leaves sharded, node-axis leaves replicated
    assert sh.senders.spec == PartitionSpec("data")
    assert sh.edge_mask.spec == PartitionSpec("data")
    assert sh.nodes.spec == PartitionSpec()
    placed = place_giant_batch(mesh, batch)
    assert placed.senders.sharding.spec == PartitionSpec("data")

    state_sharded = create_train_state(variables, tx, seed=0)
    state_sharded, loss_sharded, _ = step(state_sharded, placed)

    np.testing.assert_allclose(float(loss_plain), float(loss_sharded), rtol=1e-6)
    for a, b in zip(
        jax.tree_util.tree_leaves(jax.device_get(state_plain.params)),
        jax.tree_util.tree_leaves(jax.device_get(state_sharded.params)),
    ):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)


def pytest_dp_edge_composed_matches_data_parallel():
    """DP x edge-sharding on a 2-D (data, edge) mesh must produce the
    same loss and parameter update as the plain data-parallel shard_map
    step on a data-only mesh (same stacked batch)."""
    from hydragnn_tpu.data.synthetic import deterministic_graph_data
    from hydragnn_tpu.data.ingest import prepare_dataset
    from hydragnn_tpu.data.loader import GraphLoader
    from hydragnn_tpu.models.create import create_model_config
    from hydragnn_tpu.parallel import (
        make_mesh,
        make_sharded_train_step,
        place_state,
    )
    from hydragnn_tpu.parallel.edge_sharded import (
        make_dp_edge_train_step,
        place_dp_edge_batch,
    )
    from hydragnn_tpu.train import create_train_state, select_optimizer
    from test_data_pipeline import base_config

    d_data, d_edge = 2, 4
    cfg = base_config(multihead=False)
    cfg["NeuralNetwork"]["Architecture"]["model_type"] = "GIN"
    cfg["NeuralNetwork"]["Training"]["batch_size"] = 8
    samples = deterministic_graph_data(number_configurations=32, seed=5)
    train, _, _, _, _ = prepare_dataset(samples, cfg)
    from hydragnn_tpu.utils.config import update_config

    cfg = update_config(cfg, train, train, train)
    loader = GraphLoader(
        train, 8, shuffle=False, device_stack=d_data, edge_multiple=d_edge * 2
    )
    example_one = jax.tree_util.tree_map(lambda x: x[0], next(iter(loader)))
    model, variables = create_model_config(cfg["NeuralNetwork"], example_one)
    tx = select_optimizer({"Optimizer": {"type": "SGD", "learning_rate": 0.05}})

    # reference: shard_map DP over a 2-device data mesh
    mesh_dp = make_mesh(d_data)
    state_a = place_state(mesh_dp, create_train_state(variables, tx, seed=0))
    step_a = make_sharded_train_step(model, tx, mesh_dp)

    # composed: vmap-DP x GSPMD edge sharding over a (2, 4) mesh
    from jax.sharding import Mesh

    devs = np.array(jax.devices()[: d_data * d_edge]).reshape(d_data, d_edge)
    mesh_2d = Mesh(devs, ("data", "edge"))
    state_b = create_train_state(variables, tx, seed=0)
    step_b = make_dp_edge_train_step(model, tx, mesh_2d)

    # every batch: the last one is partial (unequal real-graph counts per
    # shard), exercising the unweighted-grad / weighted-metric contract
    for batch in loader:
        placed = place_dp_edge_batch(mesh_2d, batch)
        assert placed.senders.sharding.spec == jax.sharding.PartitionSpec(
            "data", "edge"
        )
        state_a, loss_a, tasks_a = step_a(state_a, batch)
        state_b, loss_b, tasks_b = step_b(state_b, placed)
        np.testing.assert_allclose(float(loss_a), float(loss_b), rtol=1e-5)
        np.testing.assert_allclose(
            np.asarray(tasks_a), np.asarray(tasks_b), rtol=1e-5, atol=1e-6
        )
    for a, b in zip(
        jax.tree_util.tree_leaves(jax.device_get(state_a.params)),
        jax.tree_util.tree_leaves(jax.device_get(state_b.params)),
    ):
        np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-6)


def pytest_dp_edge_placement_by_field_name():
    """place_dp_edge_batch selects edge leaves by GraphBatch field name:
    a graph- or node-axis leaf whose pad coincidentally equals the edge
    pad must NOT get the (data, edge) sharding."""
    from jax.sharding import Mesh, PartitionSpec as P

    from hydragnn_tpu.data.synthetic import deterministic_graph_data
    from hydragnn_tpu.data.ingest import prepare_dataset
    from hydragnn_tpu.data.loader import GraphLoader
    from hydragnn_tpu.parallel.edge_sharded import place_dp_edge_batch
    from test_data_pipeline import base_config

    d_data, d_edge = 2, 2
    samples = deterministic_graph_data(number_configurations=16, seed=5)
    train, _, _, _, _ = prepare_dataset(samples, base_config(multihead=False))
    loader = GraphLoader(train, 8, shuffle=False, device_stack=d_data, edge_multiple=2)
    batch = next(iter(loader))
    e_pad = batch.senders.shape[1]

    # force the collision: pad the graph axis out to the edge pad
    import dataclasses

    g = batch.graph_mask.shape[1]
    grow = e_pad - g
    assert grow > 0

    def pad_graph_axis(x):
        return np.concatenate(
            [np.asarray(x), np.zeros((x.shape[0], grow) + x.shape[2:], x.dtype)],
            axis=1,
        )

    batch = dataclasses.replace(
        batch,
        graph_mask=pad_graph_axis(batch.graph_mask),
        n_node=pad_graph_axis(batch.n_node),
        n_edge=pad_graph_axis(batch.n_edge),
        graph_targets={k: pad_graph_axis(v) for k, v in batch.graph_targets.items()},
    )
    assert batch.graph_mask.shape[1] == e_pad  # collision in place

    devs = np.array(jax.devices()[: d_data * d_edge]).reshape(d_data, d_edge)
    mesh = Mesh(devs, ("data", "edge"))
    placed = place_dp_edge_batch(mesh, batch)

    assert placed.senders.sharding.spec == P("data", "edge")
    assert placed.edge_mask.sharding.spec == P("data", "edge")
    # the colliding graph-axis leaves stay data-sharded only
    assert placed.graph_mask.sharding.spec == P("data")
    for v in placed.graph_targets.values():
        assert v.sharding.spec == P("data")


def pytest_giant_graph_e2e_120k_nodes():
    """The giant-graph demo at full scale in CI (VERDICT r01 item 10):
    120k-node periodic lattice, edges sharded over the 8-device mesh via
    place_giant_batch, plain jitted training steps partitioned by GSPMD;
    asserts O(E/D) per-device edge residency and decreasing loss."""
    import os
    import sys

    sys.path.insert(
        0, os.path.join(os.path.dirname(__file__), os.pardir, "examples", "giant_graph")
    )
    from train_giant import build_giant_problem, check_edge_residency

    from hydragnn_tpu.train import create_train_state, make_train_step, select_optimizer

    model, variables, placed, mesh = build_giant_problem(
        nx=50, ny=50, nz=48, hidden=16, n_devices=D
    )
    assert placed.nodes.shape[0] >= 100_000
    acct = check_edge_residency(placed, D)
    assert acct["senders"]["rows_per_device"] * D == acct["senders"]["global_rows"]

    tx = select_optimizer({"Optimizer": {"type": "AdamW", "learning_rate": 0.02}})
    state = create_train_state(variables, tx, seed=0)
    step = make_train_step(model, tx)
    losses = []
    for _ in range(4):
        state, loss, _ = step(state, placed)
        losses.append(float(np.asarray(loss)))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0], losses


def pytest_giant_graph_pna_with_kernel_path(monkeypatch):
    """VERDICT r02 item 2 'done' criterion: the PNA train step over a
    place_giant_batch-sharded graph with the Pallas kernel dispatch
    ACTIVE (HYDRAGNN_PALLAS=interpret on the CPU mesh) must partition
    via the kernel's custom_partitioning rule — no escape hatch — and
    match the unsharded step's loss and update exactly."""
    from hydragnn_tpu.graph import batch_graphs
    from hydragnn_tpu.models import ModelConfig, create_model
    from hydragnn_tpu.parallel.edge_sharded import place_giant_batch
    from hydragnn_tpu.train import create_train_state, make_train_step, select_optimizer

    rng = np.random.default_rng(3)
    n, e = 96, 2048
    senders = rng.integers(0, n, e).astype(np.int32)
    receivers = np.sort(rng.integers(0, n, e)).astype(np.int32)
    g = {
        "x": rng.normal(size=(n, 8)).astype(np.float32),
        "senders": senders,
        "receivers": receivers,
        "graph_targets": {"energy": np.asarray([0.7], np.float32)},
    }
    batch = batch_graphs([g], n_node_pad=n + 8, n_edge_pad=e + 2 * D, n_graph_pad=2)

    cfg = ModelConfig(
        model_type="PNA",
        input_dim=8,
        hidden_dim=128,  # 128-lane multiple: the kernel path engages
        output_dim=(1,),
        output_type=("graph",),
        output_names=("energy",),
        task_weights=(1.0,),
        num_conv_layers=2,
        graph_num_sharedlayers=1,
        graph_dim_sharedlayers=8,
        graph_num_headlayers=1,
        graph_dim_headlayers=(8,),
        pna_avg_deg_lin=20.0,
        pna_avg_deg_log=3.0,
    )
    model, variables = create_model(cfg, batch)
    tx = select_optimizer({"Optimizer": {"type": "SGD", "learning_rate": 0.05}})

    monkeypatch.setenv("HYDRAGNN_PALLAS", "interpret")
    step = make_train_step(model, tx)
    state_plain = create_train_state(variables, tx, seed=0)
    state_plain, loss_plain, _ = step(state_plain, batch)

    mesh = make_mesh(D)
    placed = place_giant_batch(mesh, batch)
    assert placed.senders.sharding.spec == jax.sharding.PartitionSpec("data")
    state_sharded = create_train_state(variables, tx, seed=0)
    state_sharded, loss_sharded, _ = step(state_sharded, placed)

    np.testing.assert_allclose(float(loss_plain), float(loss_sharded), rtol=1e-5)
    for a, b in zip(
        jax.tree_util.tree_leaves(jax.device_get(state_plain.params)),
        jax.tree_util.tree_leaves(jax.device_get(state_sharded.params)),
    ):
        np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-6)
