"""Edge-sharded giant-graph aggregation on the 8-device CPU mesh:
partitioned results must match the single-device reference exactly."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from hydragnn_tpu.parallel import make_mesh
from hydragnn_tpu.parallel.edge_sharded import (
    edge_sharded_aggregate,
    edge_sharded_gin_layer,
    place_edge_shards,
    shard_edges,
)

D = 8


@pytest.fixture
def giant_graph():
    rng = np.random.default_rng(0)
    n, e, h = 300, 5000, 16
    nodes = rng.normal(size=(n, h)).astype(np.float32)
    senders = rng.integers(0, n, e).astype(np.int32)
    receivers = rng.integers(0, n, e).astype(np.int32)
    return nodes, senders, receivers


def pytest_edge_sharded_sum_matches_reference(giant_graph):
    nodes, senders, receivers = giant_graph
    n = nodes.shape[0]
    mesh = make_mesh(D)
    snd, rcv, _, mask = shard_edges(senders, receivers, None, D)
    snd, rcv, mask = place_edge_shards(mesh, snd, rcv, mask)

    agg = edge_sharded_aggregate(
        mesh, lambda x_i, x_j: x_j, jnp.asarray(nodes), snd, rcv, mask
    )
    ref = jax.ops.segment_sum(nodes[senders], jnp.asarray(receivers), n)
    np.testing.assert_allclose(np.asarray(agg), np.asarray(ref), rtol=1e-5, atol=1e-5)


def pytest_edge_sharded_with_edge_data(giant_graph):
    nodes, senders, receivers = giant_graph
    n = nodes.shape[0]
    rng = np.random.default_rng(1)
    weights = rng.normal(size=(len(senders), 1)).astype(np.float32)
    mesh = make_mesh(D)
    snd, rcv, w, mask = shard_edges(senders, receivers, weights, D)
    snd, rcv, w, mask = place_edge_shards(mesh, snd, rcv, w, mask)

    agg = edge_sharded_aggregate(
        mesh,
        lambda x_i, x_j, ew: x_j * ew,
        jnp.asarray(nodes),
        snd,
        rcv,
        mask,
        edge_data=w,
    )
    ref = jax.ops.segment_sum(
        nodes[senders] * weights, jnp.asarray(receivers), n
    )
    np.testing.assert_allclose(np.asarray(agg), np.asarray(ref), rtol=1e-4, atol=1e-4)


def pytest_edge_sharded_gin_layer_jits(giant_graph):
    nodes, senders, receivers = giant_graph
    h = nodes.shape[1]
    rng = np.random.default_rng(2)
    w1 = rng.normal(size=(h, h)).astype(np.float32) * 0.1
    w2 = rng.normal(size=(h, h)).astype(np.float32) * 0.1
    b1 = np.zeros(h, np.float32)
    b2 = np.zeros(h, np.float32)
    mesh = make_mesh(D)
    snd, rcv, _, mask = shard_edges(senders, receivers, None, D)
    snd, rcv, mask = place_edge_shards(mesh, snd, rcv, mask)

    fn = jax.jit(
        lambda nd: edge_sharded_gin_layer(
            mesh, nd, snd, rcv, mask, w1, b1, w2, b2
        )
    )
    out = fn(jnp.asarray(nodes))
    assert out.shape == nodes.shape
    assert np.isfinite(np.asarray(out)).all()
    # eps-scaled self term dominates for isolated nodes: check a node with
    # no incoming edges matches the pure-MLP path
    iso = np.setdiff1d(np.arange(nodes.shape[0]), np.unique(receivers))
    if len(iso):
        i = int(iso[0])
        ref = jax.nn.relu((101.0 * nodes[i]) @ w1 + b1) @ w2 + b2
        np.testing.assert_allclose(np.asarray(out)[i], np.asarray(ref), rtol=1e-4)
