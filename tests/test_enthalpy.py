"""Formation enthalpy / Gibbs conversion tests (reference:
tests/test_enthalpy.py:21-65 — with linear-only targets every formation
enthalpy must be exactly 0)."""

import os

import numpy as np

from hydragnn_tpu.data.synthetic import write_lsms_files
from hydragnn_tpu.tools import (
    compositional_histogram_cutoff,
    convert_raw_data_energy_to_gibbs,
)


def _make_binary_dataset(dir, num_config=10):
    write_lsms_files(dir, num_config, number_types=2, linear_only=True)
    # pure components (reference builds one file per pure element)
    write_lsms_files(dir, 1, configuration_start=num_config, types=[0],
                     linear_only=True)
    write_lsms_files(dir, 1, configuration_start=num_config + 1, types=[1],
                     linear_only=True)


def pytest_formation_enthalpy(tmp_path):
    dir = str(tmp_path / "unit_test_enthalpy")
    _make_binary_dataset(dir)
    new_dir = convert_raw_data_energy_to_gibbs(dir, [0, 1], create_plots=False)
    files = os.listdir(new_dir)
    assert len(files) == 12
    for filename in files:
        enthalpy = np.loadtxt(os.path.join(new_dir, filename), max_rows=1)
        assert abs(float(np.atleast_1d(enthalpy)[0])) < 1e-8


def pytest_gibbs_temperature_lowers_energy(tmp_path):
    dir = str(tmp_path / "unit_test_gibbs")
    _make_binary_dataset(dir)
    hot = convert_raw_data_energy_to_gibbs(
        dir, [0, 1], temperature_kelvin=1000.0, create_plots=False,
        overwrite_data=True,
    )
    # mixed configurations must have strictly negative Gibbs energy at T>0
    # (enthalpy 0 minus T * positive entropy); pure ones stay exactly 0
    n_mixed = sum(
        len(np.unique(np.loadtxt(os.path.join(dir, f), skiprows=1,
                                 ndmin=2)[:, 0])) > 1
        for f in os.listdir(dir)
    )
    n_negative = 0
    for filename in os.listdir(hot):
        g = float(np.atleast_1d(
            np.loadtxt(os.path.join(hot, filename), max_rows=1))[0])
        assert g <= 1e-12
        if g < -1e-12:
            n_negative += 1
    assert n_negative == n_mixed > 0


def pytest_histogram_cutoff(tmp_path):
    dir = str(tmp_path / "unit_test_cutoff")
    _make_binary_dataset(dir, num_config=20)
    out = compositional_histogram_cutoff(
        dir, [0, 1], histogram_cutoff=2, num_bins=5, create_plots=False,
    )
    kept = os.listdir(out)
    assert 0 < len(kept) <= 5 * 2
    # symlinks resolve to original files
    for f in kept:
        assert os.path.exists(os.path.join(out, f))
