"""Generate the GDB9-format fixture under tests/data/gdb9_fixture/.

WHAT THIS IS (honesty note — read before citing): the build environment
has ZERO network egress, so the genuine GDB9/QM9 download
(quantum-machine.org, FigShare) is unreachable. This generator instead
produces ~100 molecules that are

  - REAL molecular species: valence-correct acyclic CHNOF molecules
    drawn from the GDB9 universe (<= 9 heavy atoms, H-saturated —
    alkanes, amines, alcohols, ethers, fluorides and their combinations),
  - with IDEALIZED geometries (standard bond lengths, tetrahedral
    embedding, steric-clash rejection) rather than DFT-relaxed ones,
  - in the EXACT GDB9 raw file format (dsgdb9nsd_*.xyz): atom count;
    "gdb <i>" + 15 scalar properties; per-atom symbol/x/y/z/Mulliken
    lines; harmonic frequencies; SMILES; InChI — including the Fortran
    ``*^`` float notation GDB9 uses, sprinkled over coordinates and
    charges to exercise the parser,
  - with SURROGATE property values: the free-energy target (column
    G, props index 13 — examples/qm9/qm9.py:G_INDEX) is a smooth
    function of the true geometry/composition (element contributions +
    pair term), so parse -> ingest -> train -> predict is a real
    learning problem; the other 14 columns are plausible-scale fillers.

The fixture's purpose is to pin the raw-GDB9 PARSER path and the
end-to-end example flow (VERDICT r02 item 6 / missing item 2) — not to
claim DFT accuracy. Swap in the real download at examples/qm9
--data dataset/qm9/raw and nothing else changes.

Regenerate: python tests/data/make_gdb9_fixture.py
"""

from __future__ import annotations

import os

import numpy as np

OUT = os.path.join(os.path.dirname(os.path.abspath(__file__)), "gdb9_fixture")

SYM = {1: "H", 6: "C", 7: "N", 8: "O", 9: "F"}
VALENCE = {6: 4, 7: 3, 8: 2, 9: 1}
BOND = {  # idealized single-bond lengths, Angstrom
    (6, 6): 1.54, (6, 7): 1.47, (6, 8): 1.43, (6, 9): 1.35,
    (7, 7): 1.45, (7, 8): 1.40, (8, 8): 1.48,
    (7, 9): 1.42, (8, 9): 1.41,
    (1, 6): 1.09, (1, 7): 1.01, (1, 8): 0.96, (1, 9): 0.92,
}
ENEG = {1: 2.20, 6: 2.55, 7: 3.04, 8: 3.44, 9: 3.98}
# additive atomic contributions (Hartree-scale), the learnable signal
CONTRIB = {1: -0.5, 6: -38.0, 7: -54.5, 8: -75.0, 9: -99.7}

_T = np.asarray(
    [[1, 1, 1], [1, -1, -1], [-1, 1, -1], [-1, -1, 1]], np.float64
) / np.sqrt(3.0)


def _bond(a: int, b: int) -> float:
    return BOND[(min(a, b), max(a, b))]


def _rot_to(v: np.ndarray) -> np.ndarray:
    """Rotation matrix mapping _T[0] onto unit vector v."""
    a, b = _T[0], v / np.linalg.norm(v)
    c = float(a @ b)
    if c > 0.9999:
        return np.eye(3)
    if c < -0.9999:
        return -np.eye(3)
    axis = np.cross(a, b)
    s = np.linalg.norm(axis)
    axis = axis / s
    k = np.asarray(
        [[0, -axis[2], axis[1]], [axis[2], 0, -axis[0]], [-axis[1], axis[0], 0]]
    )
    return np.eye(3) + s * k + (1 - c) * (k @ k)


def _twist(v: np.ndarray, angle: float) -> np.ndarray:
    """Rotation about axis v by angle."""
    v = v / np.linalg.norm(v)
    k = np.asarray([[0, -v[2], v[1]], [v[2], 0, -v[0]], [-v[1], v[0], 0]])
    return np.eye(3) + np.sin(angle) * k + (1 - np.cos(angle)) * (k @ k)


def build_molecule(rng: np.random.Generator):
    """One valence-correct acyclic CHNOF molecule with an idealized 3D
    embedding. Returns (Z list, pos [n,3], heavy_tree edges) or None if
    the embedding has a steric clash (caller retries)."""
    n_heavy = int(rng.integers(2, 10))
    zs = [6] + [
        int(rng.choice([6, 7, 8, 9], p=[0.62, 0.15, 0.15, 0.08]))
        for _ in range(n_heavy - 1)
    ]
    # random tree over heavy atoms respecting valence
    deg = [0] * n_heavy
    parent = [-1] * n_heavy
    for i in range(1, n_heavy):
        cands = [j for j in range(i) if deg[j] < VALENCE[zs[j]]]
        if not cands:
            return None
        # prefer recent atoms: chain-like molecules, fewer clashes
        w = np.asarray([1.0 + 3.0 * (j / i) for j in cands])
        parent[i] = int(rng.choice(cands, p=w / w.sum()))
        deg[parent[i]] += 1
        deg[i] += 1

    # append hydrogens to fill valences
    all_z = list(zs)
    all_parent = list(parent)
    for i in range(n_heavy):
        for _ in range(VALENCE[zs[i]] - deg[i]):
            all_z.append(1)
            all_parent.append(i)

    n = len(all_z)
    children = [[] for _ in range(n)]
    for i in range(1, n):
        children[all_parent[i]].append(i)

    pos = np.zeros((n, 3))
    # BFS embedding with tetrahedral directions + deterministic twist
    order = [0]
    dirs_of = {}
    r0 = _twist(np.asarray([0.0, 0.0, 1.0]), float(rng.uniform(0, 2 * np.pi)))
    dirs_of[0] = (_T @ r0.T, 0)  # (direction set, next free slot)
    while order:
        i = order.pop(0)
        dset, used = dirs_of[i]
        for ch in children[i]:
            d = dset[used]
            used += 1
            pos[ch] = pos[i] + d * _bond(all_z[i], all_z[ch])
            back = -d
            rot = _rot_to(back)
            tw = _twist(back, float(rng.uniform(0, 2 * np.pi)))
            dirs_of[ch] = ((_T[1:] @ rot.T) @ tw.T, 0)
            order.append(ch)
        dirs_of[i] = (dset, used)

    # steric check between non-bonded atoms
    bonded = {(min(i, all_parent[i]), max(i, all_parent[i])) for i in range(1, n)}
    d2 = np.linalg.norm(pos[:, None] - pos[None, :], axis=-1) + np.eye(n) * 9.9
    for i in range(n):
        for j in range(i + 1, n):
            if (i, j) not in bonded and d2[i, j] < 1.25:
                return None
    heavy_edges = [(i, parent[i]) for i in range(1, n_heavy)]
    return all_z, pos, heavy_edges


def smiles_of(zs, heavy_edges, n_heavy) -> str:
    """Minimal valid SMILES for the heavy-atom tree (H implicit)."""
    adj = [[] for _ in range(n_heavy)]
    for a, b in heavy_edges:
        adj[a].append(b)
        adj[b].append(a)

    def dfs(i, prev):
        s = SYM[zs[i]]
        kids = [j for j in adj[i] if j != prev]
        if not kids:
            return s
        *branches, last = kids
        return s + "".join(f"({dfs(j, i)})" for j in branches) + dfs(last, i)

    return dfs(0, -1)


def formula_of(zs) -> str:
    from collections import Counter

    c = Counter(SYM[z] for z in zs)
    out = ""
    for sym in ("C", "H", "F", "N", "O"):  # Hill-ish order
        if c[sym]:
            out += sym + (str(c[sym]) if c[sym] > 1 else "")
    return out


def _fortran(x: float) -> str:
    """GDB9's Fortran-style float: mantissa*^exponent."""
    s = f"{x:.6e}"
    mant, exp = s.split("e")
    return f"{mant}*^{int(exp)}"


def free_energy(zs, pos) -> float:
    """The learnable surrogate target: additive element contributions +
    smooth pair interaction over the ACTUAL geometry (same functional
    family as examples/qm9 generate_synthetic_qm9, so thresholds
    transfer)."""
    g = sum(CONTRIB[z] for z in zs)
    d = np.linalg.norm(pos[:, None] - pos[None, :], axis=-1)
    np.fill_diagonal(d, np.inf)
    return float(g - 2.0 * np.exp(-d / 1.5).sum() / 2.0)


def write_molecule(idx: int, zs, pos, heavy_edges, rng) -> str:
    n = len(zs)
    n_heavy = sum(1 for z in zs if z != 1)
    g = free_energy(zs, pos)
    r2 = float((pos**2).sum())
    n_hetero = sum(1 for z in zs if z in (7, 8, 9))
    mu = round(0.4 + 0.9 * n_hetero + 0.1 * float(rng.normal()), 4)
    homo = round(-0.24 - 0.01 * n_hetero + 0.005 * float(rng.normal()), 4)
    lumo = round(0.03 + 0.008 * float(rng.normal()), 4)
    props = [
        round(3.0 + 8.0 / max(n_heavy, 1), 5),  # A (GHz)
        round(1.0 + 2.0 / max(n_heavy, 1), 5),  # B
        round(0.8 + 1.5 / max(n_heavy, 1), 5),  # C
        mu, round(6.0 + 1.4 * n_heavy, 2),       # mu, alpha
        homo, lumo, round(lumo - homo, 4),       # homo, lumo, gap
        round(r2, 4),                            # <R^2>
        round(0.015 * n, 5),                     # zpve
        round(g + 0.02, 5), round(g + 0.025, 5), round(g + 0.026, 5),  # U0,U,H
        round(g, 5),                             # G  <- index 13, the target
        round(4.0 + 2.2 * n_heavy, 3),           # Cv
    ]
    # Mulliken charges: electronegativity-weighted, tiny
    qs = np.asarray([ENEG[z] - 2.55 for z in zs])
    qs = qs - qs.mean()
    lines = [str(n)]
    ptoks = []
    for k, p in enumerate(props):
        # exercise the Fortran float path on a deterministic subset
        if (idx + k) % 7 == 0:
            ptoks.append(_fortran(float(p)))
        else:
            ptoks.append(f"{p:g}")
    lines.append("gdb " + str(idx) + "\t" + "\t".join(ptoks))
    for i in range(n):
        q = qs[i] * 0.12
        qtok = _fortran(q) if (idx + i) % 5 == 0 else f"{q: .6f}"
        x, y, z = pos[i]
        xtok = _fortran(float(x)) if (idx + i) % 11 == 0 else f"{x: .7f}"
        lines.append(f"{SYM[zs[i]]}\t{xtok}\t{y: .7f}\t{z: .7f}\t{qtok}")
    freqs = sorted(abs(rng.normal(1500, 700)) for _ in range(min(3 * n - 6, 9)))
    lines.append("\t".join(f"{f:.4f}" for f in freqs))
    smi = smiles_of(zs, heavy_edges, n_heavy)
    lines.append(f"{smi}\t{smi}")
    inchi = f"InChI=1S/{formula_of(zs)}"
    lines.append(f"{inchi}\t{inchi}")
    return "\n".join(lines) + "\n"


def main(n_molecules: int = 100, seed: int = 20260731) -> None:
    os.makedirs(OUT, exist_ok=True)
    rng = np.random.default_rng(seed)
    made = 0
    while made < n_molecules:
        mol = build_molecule(rng)
        if mol is None:
            continue
        zs, pos, heavy_edges = mol
        made += 1
        text = write_molecule(made, zs, pos, heavy_edges, rng)
        with open(os.path.join(OUT, f"dsgdb9nsd_{made:06d}.xyz"), "w") as f:
            f.write(text)
    print(f"wrote {made} molecules to {OUT}")


if __name__ == "__main__":
    main()
