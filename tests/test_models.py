"""Model chassis tests: all 7 conv flavors forward/loss/grad + padding invariance."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from hydragnn_tpu.graph import batch_graphs, pad_batch
from hydragnn_tpu.models import HydraModel, ModelConfig, create_model, model_loss

ALL_MODELS = ["GIN", "SAGE", "MFC", "CGCNN", "GAT", "PNA", "SchNet"]


def make_graphs(num=3, feat=2, with_edge_attr=False, seed=0):
    rng = np.random.RandomState(seed)
    graphs = []
    for gi in range(num):
        n = rng.randint(3, 7)
        # ring graph, bidirectional
        s = np.concatenate([np.arange(n), np.roll(np.arange(n), 1)]).astype(np.int32)
        r = np.concatenate([np.roll(np.arange(n), 1), np.arange(n)]).astype(np.int32)
        pos = rng.rand(n, 3).astype(np.float32)
        g = {
            "x": rng.rand(n, feat).astype(np.float32),
            "senders": s,
            "receivers": r,
            "pos": pos,
            "graph_targets": {"energy": np.array([rng.rand()])},
            "node_targets": {"charge": rng.rand(n, 1).astype(np.float32)},
        }
        if with_edge_attr:
            g["edge_attr"] = (pos[r] - pos[s]).astype(np.float32)
        graphs.append(g)
    return graphs


def make_cfg(model_type, feat=2, hidden=8, with_edge_attr=False, node_head="mlp", num_nodes=None):
    edge_dim = 3 if with_edge_attr else None
    return ModelConfig(
        model_type=model_type,
        input_dim=feat,
        hidden_dim=feat if model_type == "CGCNN" else hidden,
        output_dim=(1, 1),
        output_type=("graph", "node"),
        output_names=("energy", "charge"),
        task_weights=(1.0, 1.0),
        num_conv_layers=2,
        graph_num_sharedlayers=2,
        graph_dim_sharedlayers=4,
        graph_num_headlayers=2,
        graph_dim_headlayers=(8, 8),
        node_num_headlayers=2,
        node_dim_headlayers=(4, 4),
        node_head_type=node_head,
        num_nodes=num_nodes,
        edge_dim=edge_dim,
        max_neighbours=4,
        pna_avg_deg_lin=2.0,
        pna_avg_deg_log=1.1,
        num_gaussians=10,
        num_filters=16,
        radius=2.0,
    )


@pytest.mark.parametrize("model_type", ALL_MODELS)
def test_forward_shapes_and_loss(model_type):
    graphs = make_graphs(with_edge_attr=(model_type in ("PNA", "CGCNN", "SchNet")))
    batch = batch_graphs(graphs)
    cfg = make_cfg(model_type, with_edge_attr=(model_type in ("PNA", "CGCNN", "SchNet")))
    model, variables = create_model(cfg, batch)

    outputs = model.apply(variables, batch, train=False)
    assert outputs[0].shape == (batch.num_graphs, 1)
    assert outputs[1].shape == (batch.num_nodes, 1)
    assert all(np.isfinite(np.asarray(o)).all() for o in outputs)

    total, tasks = model_loss(cfg, outputs, batch)
    assert np.isfinite(float(total))
    assert len(tasks) == 2


@pytest.mark.parametrize("model_type", ALL_MODELS)
def test_gradients_flow(model_type):
    graphs = make_graphs(with_edge_attr=(model_type in ("PNA", "CGCNN", "SchNet")))
    batch = batch_graphs(graphs)
    cfg = make_cfg(model_type, with_edge_attr=(model_type in ("PNA", "CGCNN", "SchNet")))
    model, variables = create_model(cfg, batch)

    def loss_fn(params):
        outputs, _ = model.apply(
            {"params": params, "batch_stats": variables["batch_stats"]},
            batch,
            train=True,
            mutable=["batch_stats"],
            rngs={"dropout": jax.random.PRNGKey(0)},
        )
        total, _ = model_loss(cfg, outputs, batch)
        return total

    grads = jax.grad(loss_fn)(variables["params"])
    norms = [float(jnp.abs(g).sum()) for g in jax.tree_util.tree_leaves(grads)]
    assert all(np.isfinite(norms))
    assert sum(n > 0 for n in norms) > len(norms) // 2, "most params should get gradient"


@pytest.mark.parametrize("model_type", ["GIN", "PNA", "GAT", "SchNet"])
def test_padding_invariance(model_type):
    """Growing the padding must not change outputs on real slots."""
    graphs = make_graphs(with_edge_attr=(model_type in ("PNA", "SchNet")))
    b1 = batch_graphs(graphs)
    b2 = pad_batch(b1, b1.num_nodes + 16, b1.num_edges + 16, b1.num_graphs + 3)
    cfg = make_cfg(model_type, with_edge_attr=(model_type in ("PNA", "SchNet")))
    model, variables = create_model(cfg, b1)

    o1 = model.apply(variables, b1, train=False)
    o2 = model.apply(variables, b2, train=False)
    np.testing.assert_allclose(
        np.asarray(o1[0][: len(graphs)]), np.asarray(o2[0][: len(graphs)]), atol=1e-5
    )
    np.testing.assert_allclose(
        np.asarray(o1[1][: b1.num_nodes]),
        np.asarray(o2[1][: b1.num_nodes]),
        atol=1e-5,
    )


def test_batchnorm_stats_ignore_padding():
    graphs = make_graphs()
    b1 = batch_graphs(graphs)
    b2 = pad_batch(b1, b1.num_nodes + 32, b1.num_edges + 32, b1.num_graphs + 3)
    cfg = make_cfg("GIN")
    model, variables = create_model(cfg, b1)

    _, s1 = model.apply(variables, b1, train=True, mutable=["batch_stats"])
    _, s2 = model.apply(variables, b2, train=True, mutable=["batch_stats"])
    l1 = jax.tree_util.tree_leaves(s1["batch_stats"])
    l2 = jax.tree_util.tree_leaves(s2["batch_stats"])
    for a, b in zip(l1, l2):
        # rtol tolerates pad-size-dependent f32 reduction order (some
        # XLA:CPU builds re-tile the masked mean/var reduce with the pad,
        # ~1e-6 rel); a real padding LEAK shifts stats by whole percents
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-5
        )


def test_mlp_per_node_head():
    # all graphs must share num_nodes for mlp_per_node (reference Base.py:209-212)
    rng = np.random.RandomState(1)
    graphs = []
    n = 4
    for _ in range(3):
        s = np.concatenate([np.arange(n), np.roll(np.arange(n), 1)]).astype(np.int32)
        r = np.concatenate([np.roll(np.arange(n), 1), np.arange(n)]).astype(np.int32)
        graphs.append(
            {
                "x": rng.rand(n, 2).astype(np.float32),
                "senders": s,
                "receivers": r,
                "pos": rng.rand(n, 3).astype(np.float32),
                "graph_targets": {"energy": np.array([1.0])},
                "node_targets": {"charge": rng.rand(n, 1).astype(np.float32)},
            }
        )
    batch = batch_graphs(graphs)
    cfg = make_cfg("GIN", node_head="mlp_per_node", num_nodes=n)
    model, variables = create_model(cfg, batch)
    outputs = model.apply(variables, batch, train=False)
    assert outputs[1].shape == (batch.num_nodes, 1)
    assert np.isfinite(np.asarray(outputs[1])).all()


def test_conv_node_head():
    graphs = make_graphs()
    batch = batch_graphs(graphs)
    cfg = make_cfg("GIN", node_head="conv")
    model, variables = create_model(cfg, batch)
    outputs = model.apply(variables, batch, train=False)
    assert outputs[1].shape == (batch.num_nodes, 1)


def test_task_weight_normalization():
    cfg = make_cfg("GIN")
    cfg2 = ModelConfig(**{**cfg.__dict__, "task_weights": (20.0, 1.0)})
    w = cfg2.normalized_weights
    np.testing.assert_allclose(sum(w), 1.0)
    np.testing.assert_allclose(w[0] / w[1], 20.0)


def test_config_validation():
    cfg = make_cfg("GIN")
    with pytest.raises(ValueError):
        ModelConfig(**{**cfg.__dict__, "model_type": "NOPE"})
    with pytest.raises(ValueError):
        ModelConfig(**{**cfg.__dict__, "task_weights": (1.0,)})
    with pytest.raises(ValueError):
        ModelConfig(**{**cfg.__dict__, "node_head_type": "mlp_per_node", "num_nodes": None})


def test_initial_bias():
    graphs = make_graphs()
    batch = batch_graphs(graphs)
    cfg = make_cfg("GIN")
    cfg = ModelConfig(**{**cfg.__dict__, "initial_bias": 7.5})
    model, variables = create_model(cfg, batch)
    bias = variables["params"]["graph_head_0"]["Dense_2"]["bias"]
    np.testing.assert_allclose(np.asarray(bias), 7.5)


def test_dynamic_radius_matches_host_builder():
    """The jittable in-forward radius graph must produce the same edge set
    as the host cell-list builder (same cutoff, nearest-K cap) on a padded
    multi-graph batch."""
    from hydragnn_tpu.data.radius_graph import radius_graph
    from hydragnn_tpu.ops.dynamic_radius import radius_graph_in_forward

    rng = np.random.RandomState(7)
    radius, cap = 0.8, 6
    graphs = []
    for gi in range(3):
        n = rng.randint(4, 8)
        pos = rng.rand(n, 3).astype(np.float32)
        ei = radius_graph(pos, radius, max_num_neighbors=cap)
        graphs.append(
            {
                "x": rng.rand(n, 2).astype(np.float32),
                "senders": ei[0].astype(np.int32),
                "receivers": ei[1].astype(np.int32),
                "pos": pos,
                "graph_targets": {"energy": np.array([0.0])},
                "node_targets": {"charge": np.zeros((n, 1), np.float32)},
            }
        )
    batch = batch_graphs(graphs, n_node_pad=32, n_edge_pad=256, n_graph_pad=4)

    senders, receivers, dist, emask = jax.jit(
        lambda b: radius_graph_in_forward(
            b.pos, b.node_graph, b.node_mask, radius, cap
        )
    )(batch)
    got = {
        (int(s), int(r))
        for s, r, m in zip(np.asarray(senders), np.asarray(receivers), np.asarray(emask))
        if m
    }
    want = {
        (int(s), int(r))
        for s, r, m in zip(
            np.asarray(batch.senders), np.asarray(batch.receivers), np.asarray(batch.edge_mask)
        )
        if m
    }
    assert got == want
    # distances on real slots must match the geometry
    pos = np.asarray(batch.pos)
    for s, r, d, m in zip(
        np.asarray(senders), np.asarray(receivers), np.asarray(dist), np.asarray(emask)
    ):
        if m:
            np.testing.assert_allclose(
                d, np.linalg.norm(pos[s] - pos[r]), rtol=1e-5, atol=1e-6
            )


def test_schnet_inforward_matches_precomputed():
    """SchNet with radius_graph_in_forward=True must produce the same
    outputs as the precomputed-edge path when the host edges were built
    with the same cutoff and cap."""
    import dataclasses

    from hydragnn_tpu.data.radius_graph import radius_graph

    rng = np.random.RandomState(11)
    radius, cap = 0.8, 6
    graphs = []
    for gi in range(3):
        n = rng.randint(4, 8)
        pos = rng.rand(n, 3).astype(np.float32)
        ei = radius_graph(pos, radius, max_num_neighbors=cap)
        graphs.append(
            {
                "x": rng.rand(n, 2).astype(np.float32),
                "senders": ei[0].astype(np.int32),
                "receivers": ei[1].astype(np.int32),
                "pos": pos,
                "graph_targets": {"energy": np.array([rng.rand()])},
                "node_targets": {"charge": rng.rand(n, 1).astype(np.float32)},
            }
        )
    batch = batch_graphs(graphs, n_node_pad=32, n_edge_pad=256, n_graph_pad=4)

    cfg = make_cfg("SchNet")
    cfg = dataclasses.replace(cfg, radius=radius, max_neighbours=cap)
    cfg_dyn = dataclasses.replace(cfg, inforward_radius=True)

    model, variables = create_model(cfg, batch)
    model_dyn = HydraModel(cfg_dyn)
    out_static = model.apply(variables, batch, train=False)
    out_dyn = model_dyn.apply(variables, batch, train=False)
    for a, b in zip(out_static, out_dyn):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5)


def test_inforward_radius_warns_on_large_pad():
    """The in-forward radius graph is O(N_pad^2); a supercell-scale node
    pad must warn at trace time instead of failing opaquely in XLA."""
    import dataclasses
    import warnings

    from hydragnn_tpu.graph.batch import pad_batch

    rng = np.random.RandomState(3)
    n = 6
    pos = rng.rand(n, 3).astype(np.float32)
    g = {
        "x": rng.rand(n, 2).astype(np.float32),
        "senders": np.array([0, 1], np.int32),
        "receivers": np.array([1, 0], np.int32),
        "pos": pos,
        "graph_targets": {"energy": np.array([0.5])},
        "node_targets": {"charge": rng.rand(n, 1).astype(np.float32)},
    }
    small = batch_graphs([g], n_node_pad=16, n_edge_pad=32, n_graph_pad=2)
    cfg = dataclasses.replace(
        make_cfg("SchNet"), radius=0.8, max_neighbours=4, inforward_radius=True
    )
    model, variables = create_model(cfg, small)
    with warnings.catch_warnings():
        warnings.simplefilter("error")  # small pad: no warning expected
        model.apply(variables, small, train=False)

    # eval_shape: the warning fires at TRACE time, so the O(N^2) build
    # itself (gigabytes of pairwise temporaries) never executes
    big = pad_batch(small, n_node=20_500, n_edge=32, n_graph=2)
    with pytest.warns(RuntimeWarning, match="O\\(N_pad\\^2\\)"):
        jax.eval_shape(lambda v, b: model.apply(v, b, train=False), variables, big)


def test_pna_decomposition_matches_message_form():
    """The r03 PNA rewrite never materializes per-edge messages; it must
    be numerically equivalent (f32) to the direct message-materializing
    form msg_e = W @ [x_i, x_j, e_ij] + b with the SAME parameters,
    including isolated (zero-degree) and padded nodes."""
    import jax
    import jax.numpy as jnp

    from hydragnn_tpu.models.convs import EdgeContext, PNAConv

    rng = np.random.RandomState(42)
    n, e, fin = 37, 180, 8
    x = jnp.asarray(rng.randn(n, fin).astype(np.float32))
    # receivers sorted (EdgeContext contract); node n-1 isolated, last
    # 20 edges masked padding
    receivers = np.sort(rng.randint(0, n - 1, e)).astype(np.int32)
    senders = rng.randint(0, n, e).astype(np.int32)
    edge_mask = np.ones(e, bool)
    edge_mask[-20:] = False
    edge_attr = jnp.asarray(rng.randn(e, 3).astype(np.float32))
    node_mask = np.ones(n, bool)
    node_mask[-2:] = False

    ctx = EdgeContext(
        senders=jnp.asarray(senders),
        receivers=jnp.asarray(receivers),
        edge_mask=jnp.asarray(edge_mask),
        node_mask=jnp.asarray(node_mask),
        edge_attr=edge_attr,
        sender_perm=jnp.argsort(jnp.asarray(senders)),
    )
    conv = PNAConv(out_dim=16, avg_deg_lin=3.0, avg_deg_log=1.2, edge_dim=3)
    params = conv.init(jax.random.PRNGKey(0), x, ctx)

    out = conv.apply(params, x, ctx)

    # ---- direct message-materializing reference with the same params ----
    p = params["params"]
    w = np.asarray(p["pre_kernel"])
    b_pre = np.asarray(p["pre_bias"])
    we_k = np.asarray(p["Dense_0"]["kernel"])
    we_b = np.asarray(p["Dense_0"]["bias"])
    post_k = np.asarray(p["Dense_1"]["kernel"])
    post_b = np.asarray(p["Dense_1"]["bias"])

    xn = np.asarray(x)
    he = np.asarray(edge_attr) @ we_k + we_b
    z = np.concatenate([xn[receivers], xn[senders], he], axis=1)
    msg = z @ w + b_pre  # [E, fin]

    msum = np.zeros((n, fin)); msq = np.zeros((n, fin)); cnt = np.zeros(n)
    mmax = np.full((n, fin), -np.inf); mmin = np.full((n, fin), np.inf)
    for i in range(e):
        if not edge_mask[i]:
            continue
        r = receivers[i]
        msum[r] += msg[i]; msq[r] += msg[i] ** 2; cnt[r] += 1
        mmax[r] = np.maximum(mmax[r], msg[i]); mmin[r] = np.minimum(mmin[r], msg[i])
    safe = np.maximum(cnt, 1.0)[:, None]
    mean = msum / safe
    std = np.sqrt(np.maximum(msq / safe - mean**2, 0.0) + 1e-5)
    mmax[~np.isfinite(mmax)] = 0.0
    mmin[~np.isfinite(mmin)] = 0.0
    agg = np.concatenate([mean, mmin, mmax, std], axis=1)

    deg = np.maximum(cnt, 1.0)
    logd = np.log(deg + 1.0)[:, None]
    scaled = np.concatenate(
        [agg, agg * (logd / 1.2), agg * (1.2 / logd), agg * (deg[:, None] / 3.0)],
        axis=1,
    )
    ref = np.concatenate([xn, scaled], axis=1) @ post_k + post_b

    np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-4, atol=2e-5)

    # gradients flow and are finite through the decomposed path
    g = jax.grad(lambda pp: (conv.apply(pp, x, ctx) ** 2).sum())(params)
    for leaf in jax.tree_util.tree_leaves(g):
        assert np.isfinite(np.asarray(leaf)).all()


def pytest_pna_dense_slot_path_matches_csr():
    """The loader-emitted dense slot map must produce the same PNA
    forward AND gradients as the CSR segment path (same batch, dense
    fields stripped)."""
    import jax
    import jax.numpy as jnp

    from hydragnn_tpu.flagship import build_flagship
    from hydragnn_tpu.train import create_train_state, select_optimizer
    from hydragnn_tpu.train.state import _train_step_body

    for edge_lengths in (False, True):
        config, model, variables, loader = build_flagship(
            n_samples=40, hidden_dim=16, num_conv_layers=2, batch_size=8,
            unit_cells=(2, 3), edge_lengths=edge_lengths,
        )
        batch = next(iter(loader))
        assert batch.dense_senders is not None  # loader emits by default
        if edge_lengths:
            assert batch.dense_edge_attr is not None
        batch_csr = batch.replace(
            dense_senders=None, dense_mask=None,
            dense_edge_attr=None,
        )
        tx = select_optimizer(config["NeuralNetwork"]["Training"])
        body = _train_step_body(model, tx)
        state = create_train_state(variables, tx, seed=0)
        _, loss_dense, _ = body(state, batch)
        _, loss_csr, _ = body(state, batch_csr)
        np.testing.assert_allclose(
            float(loss_dense), float(loss_csr), rtol=1e-5,
            err_msg=f"edge_lengths={edge_lengths}",
        )
        def loss_of(p, b):
            return body(state.replace(params=p), b)[1]

        g_dense = jax.grad(lambda p: loss_of(p, batch))(state.params)
        g_csr = jax.grad(lambda p: loss_of(p, batch_csr))(state.params)
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=5e-4, atol=5e-5
            ),
            g_dense, g_csr,
        )
