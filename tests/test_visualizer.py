"""Visualizer artifact tests (reference: hydragnn/postprocess/visualizer.py
produces scatter/histogram/global/history/node-count plots; here we assert
each method writes its file and the train-loop wiring produces plots when
Visualization.create_plots is set)."""

import os

import numpy as np

from hydragnn_tpu.postprocess.visualizer import Visualizer


def pytest_visualizer_artifacts(tmp_path):
    rng = np.random.default_rng(0)
    t = [rng.normal(size=(50, 1)), rng.normal(size=(200, 1))]
    p = [a + 0.1 * rng.normal(size=a.shape) for a in t]
    viz = Visualizer("vtest", num_heads=2, head_names=["e", "x"], log_dir=str(tmp_path))

    for path in viz.create_scatter_plots(t, p, iepoch=3):
        assert os.path.exists(path)
    for path in viz.create_error_histograms(t, p):
        assert os.path.exists(path)
    for path in viz.create_plot_global(t, p):
        assert os.path.exists(path)
    hist = {"train_loss": [1.0, 0.5], "val_loss": [1.1, 0.6], "test_loss": [1.2, 0.7]}
    assert os.path.exists(viz.plot_history(hist))
    assert os.path.exists(viz.num_nodes_plot([4, 8, 8, 16]))


def pytest_train_loop_writes_plots(tmp_path):
    import sys

    sys.path.insert(0, os.path.dirname(__file__))
    from test_train_e2e import make_config

    from hydragnn_tpu.api import run_training
    from hydragnn_tpu.data.synthetic import deterministic_graph_data
    from hydragnn_tpu.utils.config import get_log_name_config

    config = make_config("GIN", False, str(tmp_path), num_epoch=2)
    config["Visualization"] = {
        "create_plots": True,
        "plot_init_solution": True,
        "plot_hist_solution": True,
    }
    samples = deterministic_graph_data(number_configurations=40, seed=2)
    log_dir = str(tmp_path) + "/logs/"
    _, _, _, full_config = run_training(config, samples=samples, log_dir=log_dir)
    out_dir = os.path.join(log_dir, get_log_name_config(full_config))
    pngs = [f for f in os.listdir(out_dir) if f.endswith(".png")]
    assert any(f.startswith("scatter_") for f in pngs)
    assert any(f.startswith("errhist_") for f in pngs)
    assert any(f.startswith("global_") for f in pngs)
    assert any(f.startswith("global_analysis_") for f in pngs)
    assert "history.png" in pngs


def pytest_visualizer_vector_and_pernode(tmp_path):
    """The reference plot families added in r02 (visualizer.py:134-280,
    387-613): vector parity grids, per-node error histograms, per-node
    vector parity grids, global-analysis figures — asserted on an
    LSMS-style multihead layout (fixed 4-node graphs, scalar + 3-vector
    nodal heads) with non-empty axes data."""
    import matplotlib.pyplot as plt

    rng = np.random.default_rng(1)
    n_samples, n_nodes = 30, 4
    viz = Visualizer(
        "vtest2", num_heads=2, head_names=["charge", "moment"], log_dir=str(tmp_path)
    )

    # scalar nodal head: rows node-major [S * n_nodes, 1]
    t_scalar = rng.normal(size=(n_samples * n_nodes, 1))
    p_scalar = t_scalar + 0.05 * rng.normal(size=t_scalar.shape)
    # 3-vector nodal head
    t_vec = rng.normal(size=(n_samples * n_nodes, 3))
    p_vec = t_vec + 0.05 * rng.normal(size=t_vec.shape)

    paths = viz.create_reference_plot_suite(
        [t_scalar, t_vec],
        [p_scalar, p_vec],
        output_types=["node", "node"],
        nodes_per_graph=[n_nodes] * n_samples,
    )
    assert len(paths) >= 5  # vector grid, 2x per-node, 2x global analysis
    for path in paths:
        assert os.path.exists(path) and os.path.getsize(path) > 0

    names = [os.path.basename(p) for p in paths]
    assert "vector_moment.png" in names
    assert "errhist_pernode_charge.png" in names
    assert "parity_pernode_moment.png" in names
    assert "global_analysis_charge.png" in names
    assert "global_analysis_moment.png" in names

    # non-empty axes data: re-render one figure and inspect its artists
    fig_path = viz.create_parity_plot_vector("moment", t_vec, p_vec, 3)
    assert os.path.getsize(fig_path) > 0
    fig, ax = plt.subplots()
    viz._parity_panel(ax, t_vec[:, 0], p_vec[:, 0])
    assert ax.collections and ax.collections[0].get_offsets().shape[0] == len(t_vec)
    plt.close(fig)

    # ragged graph sizes: per-node panels correctly skipped, rest written
    ragged = viz.create_reference_plot_suite(
        [t_scalar], [p_scalar], output_types=["node"],
        nodes_per_graph=[3, 4] * (n_samples * 2 // 2),
    )
    assert not any("pernode" in os.path.basename(p) for p in ragged)
