"""Visualizer artifact tests (reference: hydragnn/postprocess/visualizer.py
produces scatter/histogram/global/history/node-count plots; here we assert
each method writes its file and the train-loop wiring produces plots when
Visualization.create_plots is set)."""

import os

import numpy as np

from hydragnn_tpu.postprocess.visualizer import Visualizer


def pytest_visualizer_artifacts(tmp_path):
    rng = np.random.default_rng(0)
    t = [rng.normal(size=(50, 1)), rng.normal(size=(200, 1))]
    p = [a + 0.1 * rng.normal(size=a.shape) for a in t]
    viz = Visualizer("vtest", num_heads=2, head_names=["e", "x"], log_dir=str(tmp_path))

    for path in viz.create_scatter_plots(t, p, iepoch=3):
        assert os.path.exists(path)
    for path in viz.create_error_histograms(t, p):
        assert os.path.exists(path)
    for path in viz.create_plot_global(t, p):
        assert os.path.exists(path)
    hist = {"train_loss": [1.0, 0.5], "val_loss": [1.1, 0.6], "test_loss": [1.2, 0.7]}
    assert os.path.exists(viz.plot_history(hist))
    assert os.path.exists(viz.num_nodes_plot([4, 8, 8, 16]))


def pytest_train_loop_writes_plots(tmp_path):
    import sys

    sys.path.insert(0, os.path.dirname(__file__))
    from test_train_e2e import make_config

    from hydragnn_tpu.api import run_training
    from hydragnn_tpu.data.synthetic import deterministic_graph_data
    from hydragnn_tpu.utils.config import get_log_name_config

    config = make_config("GIN", False, str(tmp_path), num_epoch=2)
    config["Visualization"] = {
        "create_plots": True,
        "plot_init_solution": True,
        "plot_hist_solution": True,
    }
    samples = deterministic_graph_data(number_configurations=40, seed=2)
    log_dir = str(tmp_path) + "/logs/"
    _, _, _, full_config = run_training(config, samples=samples, log_dir=log_dir)
    out_dir = os.path.join(log_dir, get_log_name_config(full_config))
    pngs = [f for f in os.listdir(out_dir) if f.endswith(".png")]
    assert any(f.startswith("scatter_") for f in pngs)
    assert any(f.startswith("errhist_") for f in pngs)
    assert any(f.startswith("global_") for f in pngs)
    assert "history.png" in pngs
