"""Persistent AOT executable cache (hydragnn_tpu/utils/exec_cache.py):
round-trip equivalence (a served bucket ladder and a donation-guarded
train step both bit-match their fresh compiles), corruption/truncated-
sidecar eviction, version-skew vs layout-changed classification, LRU
eviction order, two-process concurrent-writer atomicity, the donation
gate (pass + injected failure -> evict-and-recompile), and the train
loop's first-execution landing check. All CPU (conftest pins the
8-device virtual mesh); models are smoke-sized."""

import json
import os
import pickle
import subprocess
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from hydragnn_tpu.utils.exec_cache import (
    ExecCache,
    MISS_REASONS,
    _serialize_mod,
    abstract_fingerprint,
    compat_manifest,
    donation_roundtrip_ok,
    fingerprint,
)

pytestmark = pytest.mark.skipif(
    _serialize_mod() is None,
    reason="this jax cannot serialize executables (cache is inert)",
)

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _f():
    return jax.jit(lambda x: x * 2.0 + 1.0)


def _x():
    return jnp.arange(8.0, dtype=jnp.float32)


def _compile_into(cache, key=None, compat=None):
    f, x = _f(), _x()
    key = key or fingerprint("t", abstract_fingerprint((x,)))
    compat = compat or compat_manifest()
    exe, hit, _ = cache.get_or_compile(key, f, (x,), compat)
    return key, compat, exe


# ---------------------------------------------------------------------------
# core round trip + miss accounting
# ---------------------------------------------------------------------------


def test_roundtrip_hit_bitmatches_fresh_compile(tmp_path):
    cache = ExecCache(str(tmp_path))
    f, x = _f(), _x()
    key = fingerprint("t", abstract_fingerprint((x,)))
    compat = compat_manifest()
    exe, hit, _ = cache.get_or_compile(key, f, (x,), compat)
    assert not hit and cache.stats["miss_reasons"] == {"absent": 1}
    exe2, hit2, _ = cache.get_or_compile(key, f, (x,), compat)
    assert hit2 and cache.stats["hits"] == 1
    np.testing.assert_array_equal(np.asarray(exe(x)), np.asarray(exe2(x)))


def test_disabled_cache_is_inert(tmp_path):
    cache = ExecCache(None)
    assert not cache.enabled
    assert cache.load("deadbeef", compat_manifest()) is None
    assert not cache.store("deadbeef", object(), compat_manifest())
    # and no stats were recorded: no dir means no interaction happened
    assert cache.stats["misses"] == 0


# ---------------------------------------------------------------------------
# corruption -> single-entry eviction, never a crash
# ---------------------------------------------------------------------------


def test_corrupt_payload_evicts_single_entry(tmp_path):
    cache = ExecCache(str(tmp_path))
    key, compat, _ = _compile_into(cache)
    path = cache._path(key)
    with open(path, "r+b") as f:
        f.seek(20)
        f.write(b"\xff\xff\xff\xff")
    assert cache.load(key, compat) is None
    assert cache.stats["miss_reasons"]["corrupt"] == 1
    assert not os.path.exists(path) and not os.path.exists(path + ".sha256")


def test_truncated_sidecar_evicts(tmp_path):
    cache = ExecCache(str(tmp_path))
    key, compat, _ = _compile_into(cache)
    path = cache._path(key)
    with open(path + ".sha256", "w") as f:
        f.write("abc123")  # truncated/garbage digest
    assert cache.load(key, compat) is None
    assert cache.stats["miss_reasons"]["corrupt"] == 1
    assert not os.path.exists(path)


def test_unpicklable_entry_evicts(tmp_path):
    cache = ExecCache(str(tmp_path))
    key, compat, _ = _compile_into(cache)
    path = cache._path(key)
    data = b"not a pickle at all"
    with open(path, "wb") as f:
        f.write(data)
    import hashlib

    with open(path + ".sha256", "w") as f:
        f.write(hashlib.sha256(data).hexdigest())  # digest VALID, pickle not
    assert cache.load(key, compat) is None
    assert cache.stats["miss_reasons"]["corrupt"] == 1
    assert not os.path.exists(path)


# ---------------------------------------------------------------------------
# compat classification: loud, and NOT an eviction
# ---------------------------------------------------------------------------


def test_version_skew_classified_without_eviction(tmp_path):
    cache = ExecCache(str(tmp_path))
    key, compat, _ = _compile_into(cache)
    want = dict(compat, jax="0.0.0-other")
    assert cache.load(key, want) is None
    assert cache.stats["miss_reasons"] == {"absent": 1, "version_skew": 1}
    # the entry is valid for the environment that wrote it: still there
    assert os.path.exists(cache._path(key))


def test_layout_change_classified_over_version_skew(tmp_path):
    cache = ExecCache(str(tmp_path))
    key, compat, _ = _compile_into(cache)
    want = dict(compat, layout=(1, 4, 2), jax="0.0.0-other")
    assert cache.load(key, want) is None
    # layout wins the classification even when versions ALSO differ —
    # resharding is the operator-actionable cause
    assert cache.stats["miss_reasons"]["layout_changed"] == 1
    assert os.path.exists(cache._path(key))


def test_compute_dtype_is_part_of_compat(tmp_path):
    cache = ExecCache(str(tmp_path))
    key, compat, _ = _compile_into(
        cache, compat=compat_manifest(compute_dtype=jnp.bfloat16)
    )
    assert cache.load(key, compat_manifest()) is None  # f32 vs bf16
    assert cache.stats["miss_reasons"]["version_skew"] == 1


# ---------------------------------------------------------------------------
# LRU bound
# ---------------------------------------------------------------------------


def test_lru_evicts_oldest_first(tmp_path):
    cache = ExecCache(str(tmp_path), max_bytes=1 << 60)
    f = _f()
    compat = compat_manifest()
    keys = []
    for n in (8, 16, 24):
        x = jnp.arange(float(n), dtype=jnp.float32)
        key = fingerprint("lru", n)
        cache.get_or_compile(key, f, (x,), compat)
        keys.append(key)
    # age the first entry far into the past, then shrink the bound so
    # only ~2 entries fit and re-run enforcement via a fresh store
    old = time.time() - 10_000
    os.utime(cache._path(keys[0]), (old, old))
    sizes = [
        os.path.getsize(cache._path(k))
        + os.path.getsize(cache._path(k) + ".sha256")
        for k in keys
    ]
    cache.max_bytes = sizes[1] + sizes[2] + 1
    cache._enforce_lru()
    assert not os.path.exists(cache._path(keys[0]))  # oldest gone
    assert os.path.exists(cache._path(keys[1]))
    assert os.path.exists(cache._path(keys[2]))
    assert cache.stats["evictions"] == 1


def test_lru_touches_on_hit(tmp_path):
    cache = ExecCache(str(tmp_path))
    key, compat, _ = _compile_into(cache)
    old = time.time() - 10_000
    os.utime(cache._path(key), (old, old))
    assert cache.load(key, compat) is not None
    assert os.path.getmtime(cache._path(key)) > old + 5_000


# ---------------------------------------------------------------------------
# donation gate
# ---------------------------------------------------------------------------


def test_donation_probe_passes_and_persists(tmp_path):
    assert donation_roundtrip_ok(str(tmp_path))
    verdict = json.load(open(tmp_path / "donation_probe.json"))
    assert all(v is True for v in verdict.values())


def test_injected_donation_failure_evicts_and_recompiles(tmp_path, monkeypatch):
    cache = ExecCache(str(tmp_path))
    f, x = _f(), _x()
    key = fingerprint("don", abstract_fingerprint((x,)))
    compat = compat_manifest()
    exe, hit, _ = cache.get_or_compile(key, f, (x,), compat, donated=True)
    assert not hit and os.path.exists(cache._path(key))
    monkeypatch.setenv("HYDRAGNN_INJECT_DONATION_CHECK_FAIL", "1")
    # the warm load must now EVICT the entry and fall through to a live
    # compile — the forced-failure driver for the jax<0.5 staleness story
    exe2, hit2, _ = cache.get_or_compile(key, f, (x,), compat, donated=True)
    assert not hit2
    assert cache.stats["miss_reasons"]["donation_check_failed"] == 1
    # and the failing gate also blocks RE-storing the donated executable
    assert not os.path.exists(cache._path(key))
    np.testing.assert_array_equal(np.asarray(exe(x)), np.asarray(exe2(x)))


def test_undonated_load_ignores_donation_gate(tmp_path, monkeypatch):
    cache = ExecCache(str(tmp_path))
    key, compat, _ = _compile_into(cache)
    monkeypatch.setenv("HYDRAGNN_INJECT_DONATION_CHECK_FAIL", "1")
    # serving forwards are donation-free: the gate must not touch them
    assert cache.load(key, compat) is not None


# ---------------------------------------------------------------------------
# concurrent writers (two processes, same key, same dir)
# ---------------------------------------------------------------------------

_WRITER = r"""
import sys
sys.path.insert(0, {repo!r})
from __graft_entry__ import _load_platform_module
_load_platform_module().pin_virtual_cpu_mesh(1)
import jax, jax.numpy as jnp
from hydragnn_tpu.utils.exec_cache import ExecCache, compat_manifest

cache = ExecCache(sys.argv[1])
f = jax.jit(lambda x: x * 2.0 + 1.0)
compiled = f.lower(jnp.arange(8.0, dtype=jnp.float32)).compile()
for _ in range(8):
    assert cache.store("cafef00d", compiled, compat_manifest())
print("WRITER-DONE")
"""


def test_concurrent_writers_leave_valid_entry(tmp_path):
    script = tmp_path / "writer.py"
    script.write_text(_WRITER.format(repo=_REPO))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("XLA_FLAGS", None)
    procs = [
        subprocess.Popen(
            [sys.executable, str(script), str(tmp_path)],
            env=env,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
        )
        for _ in range(2)
    ]
    for p in procs:
        out, _ = p.communicate(timeout=240)
        assert p.returncode == 0 and "WRITER-DONE" in out, out[-2000:]
    # whatever interleaving happened, the published entry is COMPLETE:
    # digest sidecar matches and the payload unpickles + deserializes
    cache = ExecCache(str(tmp_path))
    assert cache.load("cafef00d", compat_manifest()) is not None
    assert not [n for n in os.listdir(tmp_path) if n.endswith(".tmp")]


# ---------------------------------------------------------------------------
# round-trip equivalence on the real consumers
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def tiny_flagship():
    from hydragnn_tpu.flagship import build_flagship
    from hydragnn_tpu.train import create_train_state, select_optimizer

    config, model, variables, loader = build_flagship(
        n_samples=24,
        hidden_dim=8,
        num_conv_layers=2,
        batch_size=4,
        unit_cells=(2, 3),
    )
    tx = select_optimizer(config["NeuralNetwork"]["Training"])
    state = create_train_state(variables, tx)
    return config, model, variables, loader, tx, state


def _copy(tree):
    return jax.tree_util.tree_map(lambda x: x.copy(), tree)


def _assert_trees_bitmatch(a, b):
    la, lb = jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def test_guarded_train_step_roundtrip_bitmatch(tmp_path, tiny_flagship):
    """The donation-guarded train step through the cache computes the
    BIT-identical update the fresh compile computes — the staleness
    failure mode the donation gate guards against would show here."""
    from hydragnn_tpu.train import make_train_step

    _, model, _, loader, tx, state = tiny_flagship
    step = make_train_step(model, tx, guard_nonfinite=True)
    batch = next(iter(loader))
    consec = jnp.zeros((), jnp.int32)
    cache = ExecCache(str(tmp_path))
    key = fingerprint("step", abstract_fingerprint((state, batch, consec)))
    compat = compat_manifest()
    fresh, hit, _ = cache.get_or_compile(
        key, step, (state, batch, consec), compat, donated=True
    )
    assert not hit
    cached, hit2, _ = cache.get_or_compile(
        key, step, (state, batch, consec), compat, donated=True
    )
    assert hit2
    out_fresh = fresh(_copy(state), batch, consec)
    out_cached = cached(_copy(state), batch, consec)
    _assert_trees_bitmatch(out_fresh, out_cached)
    # the cached step LANDS: optimizer step advanced by exactly one
    assert int(jax.device_get(out_cached[0].step)) == int(
        jax.device_get(state.step)
    ) + 1


def test_served_ladder_warm_start_zero_compiles_and_bitmatch(tmp_path, tiny_flagship):
    """Second server against the same cache dir: 0 warmup compiles,
    every bucket a disk hit, and predictions bit-match the cold
    server's — the second-replica acceptance criterion."""
    from hydragnn_tpu.serve import ModelRegistry, ModelServer, ServeConfig

    _, model, variables, loader, _, _ = tiny_flagship
    samples = list(loader.all_samples)[:6]
    registry = ModelRegistry()

    def start_and_predict(tag):
        served = registry.register(f"exec_cache_{tag}", model, variables)
        server = ModelServer(
            served,
            samples,
            ServeConfig(
                max_batch=4,
                num_buckets=2,
                exec_cache_dir=str(tmp_path),
            ),
        )
        server.start()
        preds = [server.predict(s, timeout=60) for s in samples]
        snap = server.metrics_snapshot()
        n_buckets = len(server.buckets)
        server.stop()
        return preds, snap, n_buckets

    cold_preds, cold_snap, n_buckets = start_and_predict("cold")
    assert cold_snap["compile_warmup"] == n_buckets
    assert cold_snap["exec_cache_misses"] == n_buckets
    warm_preds, warm_snap, _ = start_and_predict("warm")
    assert warm_snap["compile_warmup"] == 0
    assert warm_snap["compile_misses"] == 0
    assert warm_snap["exec_cache_hits"] == n_buckets
    for c, w in zip(cold_preds, warm_preds):
        assert sorted(c) == sorted(w)
        for k in c:
            np.testing.assert_array_equal(np.asarray(c[k]), np.asarray(w[k]))


# ---------------------------------------------------------------------------
# the train loop's first-execution landing check
# ---------------------------------------------------------------------------


def test_landing_check_passes_through_good_executable(tmp_path):
    from types import SimpleNamespace

    from hydragnn_tpu.train.loop import _landing_checked

    cache = ExecCache(str(tmp_path))
    calls = []

    def good(state, batch):
        calls.append("cached")
        return (SimpleNamespace(step=state.step + 1), 0.5)

    wrapped = _landing_checked(good, None, cache, "k", 1, "train_step")
    out = wrapped(SimpleNamespace(step=np.int32(7)), "b")
    assert int(out[0].step) == 8 and calls == ["cached"]
    wrapped(SimpleNamespace(step=np.int32(8)), "b")
    assert calls == ["cached", "cached"]
    assert cache.stats["misses"] == 0


def test_landing_check_evicts_and_falls_back_on_stale_step(tmp_path):
    """A cached executable whose update never lands (output step ==
    input step: dropped donation metadata) must be evicted with
    ``donation_check_failed`` and replaced by the fresh step, which
    replays on the saved pre-execution copy."""
    from types import SimpleNamespace

    from hydragnn_tpu.train.loop import _landing_checked

    cache = ExecCache(str(tmp_path))
    key, compat, _ = _compile_into(cache, key="stalekey")
    assert os.path.exists(cache._path("stalekey"))

    def stale(state, batch):
        return (SimpleNamespace(step=state.step), 0.5)  # never lands

    fresh_calls = []

    def fresh(state, batch):
        fresh_calls.append(int(state.step))
        return (SimpleNamespace(step=state.step + 1), 0.5)

    wrapped = _landing_checked(stale, fresh, cache, "stalekey", 1, "train_step")
    out = wrapped(SimpleNamespace(step=np.int32(3)), "b")
    assert int(out[0].step) == 4  # the fresh replay's answer
    assert fresh_calls == [3]  # replayed on the saved copy
    assert cache.stats["miss_reasons"]["donation_check_failed"] == 1
    assert not os.path.exists(cache._path("stalekey"))  # evicted
    # permanently switched: later calls go straight to fresh
    wrapped(SimpleNamespace(step=np.int32(4)), "b")
    assert fresh_calls == [3, 4]


# ---------------------------------------------------------------------------
# observability plumbing
# ---------------------------------------------------------------------------


def test_flight_events_validate(tmp_path):
    from hydragnn_tpu.obs.flight import FlightRecorder, validate_flight_record

    fpath = tmp_path / "flight.jsonl"
    flight = FlightRecorder(str(fpath))
    cache = ExecCache(str(tmp_path / "cache"), flight=flight, consumer="test")
    key, compat, _ = _compile_into(cache)
    cache.load(key, dict(compat, jax="other"))  # version_skew miss
    flight.close()
    assert validate_flight_record(str(fpath)) == []
    kinds = [
        (e["kind"], e.get("event"))
        for e in map(json.loads, open(fpath))
    ]
    assert ("exec_cache", "miss") in kinds and ("exec_cache", "store") in kinds


def test_serve_metrics_counters(tmp_path):
    from hydragnn_tpu.serve.metrics import ServeMetrics

    m = ServeMetrics(num_buckets=1)
    cache = ExecCache(str(tmp_path), metrics=m, consumer="serve")
    key, compat, _ = _compile_into(cache)  # absent miss, then store
    cache.load(key, compat)  # hit
    cache.load(key, dict(compat, jax="other"))  # version_skew miss
    snap = m.snapshot()
    assert snap["exec_cache_hits"] == 1
    assert snap["exec_cache_misses"] == 2
    assert snap["exec_cache_miss_reasons"] == {"absent": 1, "version_skew": 1}
    assert cache.manifest()["enabled"] is True


def test_miss_reasons_are_the_documented_set(tmp_path):
    # docs/PERF.md documents this table; a new reason must be added
    # there (and to obs_report's rendering) deliberately
    assert set(MISS_REASONS) == {
        "absent",
        "corrupt",
        "version_skew",
        "layout_changed",
        "donation_check_failed",
        "unavailable",
    }
