"""True multi-process pass: two OS processes with jax.distributed over a
local coordinator — the analog of the reference CI's ``mpirun -n 2``
pytest pass (reference: .github/workflows/CI.yml). Covers
setup_distributed rendezvous, cross-process collectives, the
multi-process ContainerWriter (allgather + ranged writes), and sharded
GraphLoader equalization.
"""

import os
import socket
import subprocess
import sys

import jax
import numpy as np
import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

try:
    _JAX_VER = tuple(int(p) for p in jax.__version__.split(".")[:2])
except ValueError:  # dev version string: assume current
    _JAX_VER = (99, 0)
# jax < 0.5's CPU backend rejects cross-process computations outright
# ("Multiprocess computations aren't implemented on the CPU backend"),
# so the 2-OS-process pass cannot run there at all — an environment
# limit, not a code regression; newer jax (incl. the dev TPU image)
# runs these.
requires_cpu_collectives = pytest.mark.skipif(
    _JAX_VER < (0, 5),
    reason="jax<0.5 CPU backend has no cross-process collectives",
)

_WORKER = r"""
import os, sys
import numpy as np
import jax

jax.config.update("jax_platforms", "cpu")
rank = int(sys.argv[1])
nproc = int(sys.argv[2])
port = sys.argv[3]
workdir = sys.argv[4]
repo = sys.argv[5]

jax.distributed.initialize(
    coordinator_address=f"127.0.0.1:{port}", num_processes=nproc, process_id=rank
)
assert jax.process_count() == nproc

sys.path.insert(0, repo)
from hydragnn_tpu.data.container import ContainerDataset, ContainerWriter
from hydragnn_tpu.data.dataset import GraphSample
from hydragnn_tpu.data.loader import GraphLoader
from hydragnn_tpu.parallel import barrier, get_comm_size_and_rank

size, r = get_comm_size_and_rank()
assert (size, r) == (nproc, rank), (size, r)

# cross-process collective sanity (psum over one device per process)
from jax.experimental import multihost_utils
total = multihost_utils.process_allgather(np.asarray([rank + 1.0]))
assert float(np.sum(total)) == sum(range(1, nproc + 1))

# multi-process container write: each rank contributes 3 distinct samples
rng = np.random.default_rng(100 + rank)
def chain_edges(n):
    src = np.arange(n - 1, dtype=np.int64)
    ei = np.stack([np.concatenate([src, src + 1]), np.concatenate([src + 1, src])])
    return ei

samples = []
for i in range(3):
    n = 4 + rank
    ei = chain_edges(n)
    samples.append(
        GraphSample(
            x=np.full((n, 2), rank * 10 + i, dtype=np.float64),
            pos=rng.normal(size=(n, 3)).astype(np.float32),
            graph_y=np.asarray([rank * 10.0 + i]),
            edge_index=ei,
            edge_attr=np.ones((ei.shape[1], 1), dtype=np.float32),
        )
    )
path = os.path.join(workdir, "mp_container")
w = ContainerWriter(path)
w.add(samples)
w.add_global("minmax_graph_feature", [0.0, 1.0])
w.save()
barrier("after_save")

ds = ContainerDataset(path)
assert len(ds) == 3 * nproc
# rank 0's first sample then rank 1's first sample ordering by rank ranges
got = sorted(float(ds.get(i).graph_y[0]) for i in range(len(ds)))
want = sorted(r_ * 10.0 + i for r_ in range(nproc) for i in range(3))
assert got == want, (got, want)

# sharded loader: shards cover every sample, with overlap limited to
# the wrap-around remainder (ceil-equalized DistributedSampler contract)
all_samples = ds.samples()
loaders = [
    GraphLoader(all_samples, 2, num_shards=nproc, shard_rank=p)
    for p in range(nproc)
]
lens = {len(l.samples) for l in loaders}
assert len(lens) == 1
key = lambda s: float(s.graph_y[0])
shard_keys = [sorted(key(s) for s in l.samples) for l in loaders]
union = set().union(*[set(k) for k in shard_keys])
assert union == {key(s) for s in all_samples}, "shards must cover the dataset"
total = sum(len(k) for k in shard_keys)
import math
assert total == nproc * math.ceil(len(all_samples) / nproc)
print(f"rank {rank}: OK")
"""


_TRAIN_WORKER = r"""
import os, sys

os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"

import numpy as np
import jax

# env JAX_PLATFORMS does not stick under the axon image; pin the config
# before any backend use (see .claude/skills/verify/SKILL.md)
jax.config.update("jax_platforms", "cpu")

rank = int(sys.argv[1])
nproc = int(sys.argv[2])
port = sys.argv[3]
workdir = sys.argv[4]
repo = sys.argv[5]

jax.distributed.initialize(
    coordinator_address=f"127.0.0.1:{port}", num_processes=nproc, process_id=rank
)
assert jax.process_count() == nproc
assert jax.local_device_count() == 2
assert len(jax.devices()) == 2 * nproc

sys.path.insert(0, repo)
sys.path.insert(0, os.path.join(repo, "tests"))
from test_train_e2e import make_config
from hydragnn_tpu.api import run_prediction, run_training
from hydragnn_tpu.data.synthetic import deterministic_graph_data

config = make_config("GIN", False, workdir, num_epoch=30)
# pod-scale ZeRO-1: optimizer-state leaves shard over the global mesh
config["NeuralNetwork"]["Training"]["Optimizer"]["use_zero_redundancy"] = True
samples = deterministic_graph_data(number_configurations=300, seed=0)
log_dir = os.path.join(workdir, "logs/")
model, state, history, full_config = run_training(
    config, samples=samples, log_dir=log_dir
)

# every process must hold identical (replicated, psum-synced) params
from jax.experimental import multihost_utils
leaves = jax.tree_util.tree_leaves(state.params)
flat = np.concatenate([np.asarray(l).reshape(-1) for l in leaves])
gathered = np.asarray(multihost_utils.process_allgather(flat))
for p in range(1, nproc):
    np.testing.assert_allclose(gathered[p], gathered[0], rtol=0, atol=0)

losses = history["train_loss"]
assert all(np.isfinite(losses)), losses
assert losses[-1] < 0.5 * losses[0], f"no convergence: {losses[0]} -> {losses[-1]}"

# multi-process checkpoint: orbax sharded dir written by all hosts
import glob
orbax_dirs = glob.glob(os.path.join(log_dir, "*", "*.orbax"))
assert orbax_dirs, "expected an orbax checkpoint dir"

# reload through run_prediction (orbax restore + per-process eval shards
# + cross-process varlen gather); GIN thresholds (reference:
# tests/test_graphs.py:131) with headroom for the shorter budget
config2 = make_config("GIN", False, workdir, num_epoch=30)
samples2 = deterministic_graph_data(number_configurations=300, seed=0)
error, error_rmse_task, true_values, predicted_values = run_prediction(
    config2, samples=samples2, log_dir=log_dir
)
rmse = float(error_rmse_task[0])
mae = float(np.mean(np.abs(true_values[0] - predicted_values[0])))
assert rmse < 0.35, f"RMSE {rmse}"
assert mae < 0.30, f"MAE {mae}"

# the replicated (non-ZeRO) multi-host step must also run and keep the
# pinned layout (params host-readable after the update)
from hydragnn_tpu.api import prepare_loaders_and_config
from hydragnn_tpu.parallel import make_multihost_mesh, make_sharded_train_step, place_state
from hydragnn_tpu.train import create_train_state, select_optimizer

config3 = make_config("GIN", False, workdir, num_epoch=1)
samples3 = deterministic_graph_data(number_configurations=300, seed=0)
tl3, _, _, config3 = prepare_loaders_and_config(config3, samples3, device_stack=2)
mesh3 = make_multihost_mesh(per_process=2)
tl3.set_global_mesh(mesh3)
tx3 = select_optimizer({"Optimizer": {"type": "SGD", "learning_rate": 0.001}})
variables3 = {
    "params": jax.device_get(state.params),
    "batch_stats": jax.device_get(state.batch_stats),
}
st3 = place_state(mesh3, create_train_state(variables3, tx3), zero1=False)
step3 = make_sharded_train_step(model, tx3, mesh3, zero1=False)
st3, loss3, _ = step3(st3, next(iter(tl3)))
assert np.isfinite(float(loss3)), float(loss3)
_ = np.concatenate(
    [np.asarray(l).reshape(-1) for l in jax.tree_util.tree_leaves(st3.params)]
)
print(f"rank {rank}: TRAIN-OK rmse={rmse:.4f} mae={mae:.4f} rep-step={float(loss3):.4f}")
"""


_COMPOSED_WORKER = r"""
import os, sys

os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"

import numpy as np
import jax

jax.config.update("jax_platforms", "cpu")

rank = int(sys.argv[1])
nproc = int(sys.argv[2])
port = sys.argv[3]
workdir = sys.argv[4]
repo = sys.argv[5]

jax.distributed.initialize(
    coordinator_address=f"127.0.0.1:{port}", num_processes=nproc, process_id=rank
)
assert jax.local_device_count() == 4
assert len(jax.devices()) == 4 * nproc

sys.path.insert(0, repo)
sys.path.insert(0, os.path.join(repo, "tests"))
import dataclasses
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from hydragnn_tpu.data.synthetic import deterministic_graph_data
from hydragnn_tpu.data.ingest import prepare_dataset
from hydragnn_tpu.data.loader import GraphLoader
from hydragnn_tpu.models.create import create_model_config
from hydragnn_tpu.parallel.edge_sharded import make_dp_edge_train_step
from hydragnn_tpu.parallel.sharded import place_state
from hydragnn_tpu.train import create_train_state, select_optimizer
from hydragnn_tpu.utils.config import update_config
from test_data_pipeline import base_config

d_data, d_edge = nproc, 4  # one data row per process, its 4 devices as edge axis

cfg = base_config(multihead=False)
cfg["NeuralNetwork"]["Architecture"]["model_type"] = "GIN"
cfg["NeuralNetwork"]["Training"]["batch_size"] = 8
samples = deterministic_graph_data(number_configurations=32, seed=5)
train, _, _, _, _ = prepare_dataset(samples, cfg)
cfg = update_config(cfg, train, train, train)
# every process builds the SAME full stacked batches (no sharding), then
# contributes its data row to the global mesh
loader = GraphLoader(
    train, 8, shuffle=False,
    device_stack=d_data if d_data > 1 else 1, edge_multiple=d_edge * 2,
)

def stack_one(batch):
    # nproc=1 sanity mode: the loader emits no device axis at
    # device_stack=1; the composed step still wants [D_data=1, ...]
    if d_data > 1:
        return batch
    return jax.tree_util.tree_map(lambda x: np.asarray(x)[None], batch)

example_one = jax.tree_util.tree_map(
    lambda x: x[0], stack_one(next(iter(loader)))
)
model, variables = create_model_config(cfg["NeuralNetwork"], example_one)
tx = select_optimizer({"Optimizer": {"type": "SGD", "learning_rate": 0.05}})

# single-process reference: the SAME composed step on a local mesh
# over this process's devices — identical math, no collectives
mesh_local = Mesh(
    np.array(jax.local_devices()[:4]).reshape(d_data, 4 // d_data),
    ("data", "edge"),
)
state_ref = place_state(mesh_local, create_train_state(variables, tx, seed=0))
step_ref = make_dp_edge_train_step(model, tx, mesh_local)

# composed global mesh: jax.devices() orders by (process, id), so
# reshape(nproc, 4) puts process p's devices in data row p
mesh_g = Mesh(np.array(jax.devices()).reshape(d_data, d_edge), ("data", "edge"))
state_g = place_state(mesh_g, create_train_state(variables, tx, seed=0))
step_g = make_dp_edge_train_step(model, tx, mesh_g)

EDGE_FIELDS = {"senders", "receivers", "edge_mask", "edge_attr", "sender_perm"}

def globalize_dp_edge(batch):
    # each process feeds its OWN data row (full edge axis — the edge
    # shards of a row are all local to its process)
    vals = {}
    for f in dataclasses.fields(batch):
        v = getattr(batch, f.name)
        if f.metadata.get("static"):
            vals[f.name] = v
            continue
        spec = P("data", "edge") if f.name in EDGE_FIELDS else P("data")
        sh = NamedSharding(mesh_g, spec)
        vals[f.name] = jax.tree_util.tree_map(
            lambda x: jax.make_array_from_process_local_data(
                sh, np.asarray(x)[rank : rank + 1]
            ),
            v,
        )
    return type(batch)(**vals)

from hydragnn_tpu.parallel.edge_sharded import place_dp_edge_batch

losses = []
for batch in loader:
    batch = stack_one(batch)
    placed_ref = place_dp_edge_batch(mesh_local, batch)
    state_ref, loss_ref, _ = step_ref(state_ref, placed_ref)
    placed_g = globalize_dp_edge(batch)
    assert placed_g.senders.sharding.spec == P("data", "edge")
    state_g, loss_g, _ = step_g(state_g, placed_g)
    la, lb = float(loss_ref), float(loss_g)
    losses.append((la, lb))
    np.testing.assert_allclose(la, lb, rtol=1e-4)

# final params: replicated across the global mesh, equal to the local
# reference on every process
for a, b in zip(
    jax.tree_util.tree_leaves(jax.device_get(state_ref.params)),
    jax.tree_util.tree_leaves(jax.device_get(state_g.params)),
):
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-6)
print(f"rank {rank}: COMPOSED-OK losses={losses}")
"""


_FSDP_WORKER = r"""
import os, sys

os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"

import numpy as np
import jax

jax.config.update("jax_platforms", "cpu")

rank = int(sys.argv[1])
nproc = int(sys.argv[2])
port = sys.argv[3]
workdir = sys.argv[4]
repo = sys.argv[5]

jax.distributed.initialize(
    coordinator_address=f"127.0.0.1:{port}", num_processes=nproc, process_id=rank
)
assert jax.local_device_count() == 2
assert len(jax.devices()) == 2 * nproc

sys.path.insert(0, repo)
sys.path.insert(0, os.path.join(repo, "tests"))
from hydragnn_tpu.data.synthetic import deterministic_graph_data
from hydragnn_tpu.data.ingest import prepare_dataset
from hydragnn_tpu.data.loader import GraphLoader
from hydragnn_tpu.models.create import create_model_config
from hydragnn_tpu.parallel import FSDP_AXIS, Partitioner
from hydragnn_tpu.train import create_train_state, select_optimizer
from hydragnn_tpu.utils.config import update_config
from test_data_pipeline import base_config

cfg = base_config(multihead=False)
cfg["NeuralNetwork"]["Architecture"]["model_type"] = "GIN"
cfg["NeuralNetwork"]["Training"]["batch_size"] = 8
samples = deterministic_graph_data(number_configurations=32, seed=9)
train, _, _, _, _ = prepare_dataset(samples, cfg)
cfg = update_config(cfg, train, train, train)

def fresh_loader():
    return GraphLoader(
        train, 8, shuffle=False, num_shards=nproc, shard_rank=rank, device_stack=2
    )

def sharded_over_fsdp(leaf):
    spec = leaf.sharding.spec
    return any(
        e == FSDP_AXIS or (isinstance(e, tuple) and FSDP_AXIS in e)
        for e in spec if e is not None
    )

example = jax.tree_util.tree_map(lambda x: x[0], next(iter(fresh_loader())))
model, variables = create_model_config(cfg["NeuralNetwork"], example)
tx = select_optimizer({"Optimizer": {"type": "SGD", "learning_rate": 0.05}})

# replicated multi-host reference: global (data=4) mesh
nn_rep = dict(cfg["NeuralNetwork"])
part_rep = Partitioner.from_config(nn_rep, device_stack=2, multihost=True)
loader_rep = fresh_loader()
part_rep.attach_loader(loader_rep)
st_rep = part_rep.shard_init(create_train_state(variables, tx, seed=0))
step_rep = part_rep.shard_train_step(model, tx)
st_rep, loss_rep, _ = step_rep(st_rep, next(iter(loader_rep)))
loss_rep = float(loss_rep)

# fsdp=2: global (data=2, fsdp=2) mesh, params+opt sharded intra-host
nn_f = dict(cfg["NeuralNetwork"])
nn_f["Parallel"] = {"fsdp": 2}
part_f = Partitioner.from_config(nn_f, device_stack=2, multihost=True)
# (data scales with the process count: 2 at nproc=2, 1 in the
# single-process sanity mode this worker also runs under)
assert part_f.config.data == nproc and part_f.config.fsdp == 2
loader_f = fresh_loader()
part_f.attach_loader(loader_f)
st_f = part_f.shard_init(create_train_state(variables, tx, seed=0))
n_sharded = sum(
    sharded_over_fsdp(l) for l in jax.tree_util.tree_leaves(st_f.params)
)
assert n_sharded > 0, "no fsdp-sharded params on the multihost mesh"
step_f = part_f.shard_train_step(model, tx)
st_f, loss_f, _ = step_f(st_f, next(iter(loader_f)))
loss_f = float(loss_f)

assert np.isfinite(loss_rep) and np.isfinite(loss_f)
np.testing.assert_allclose(loss_f, loss_rep, rtol=1e-5)

# both processes must agree on both losses
if nproc > 1:
    from jax.experimental import multihost_utils
    pair = np.asarray(
        multihost_utils.process_allgather(np.asarray([loss_rep, loss_f]))
    ).reshape(nproc, 2)
    np.testing.assert_allclose(pair[1], pair[0], rtol=0, atol=0)

man = part_f.manifest(state=st_f)
assert man["fsdp"] == 2 and man["params"]["sharded"] == n_sharded
assert man["params"]["bytes_per_device"] < man["params"]["bytes_global"]
print(f"rank {rank}: FSDP-OK loss={loss_f:.6f} sharded={n_sharded}")
"""


@requires_cpu_collectives
def pytest_two_process_fsdp_mesh(tmp_path):
    """2-process FSDP: a global (data=2, fsdp=2) Partitioner mesh where
    each process contributes 2 CPU devices — its fsdp group stays
    intra-host by construction. One train step must match the replicated
    multi-host data-parallel reference, with parameters committed-sharded
    over the fsdp axis and the manifest reporting the per-device byte
    drop (ISSUE 7 satellite; skip-gated like the other 2-process cases)."""
    port = _free_port()
    script = tmp_path / "fsdp_worker.py"
    script.write_text(_FSDP_WORKER)
    nproc = 2
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    procs = [
        subprocess.Popen(
            [
                sys.executable, str(script), str(r), str(nproc), str(port),
                str(tmp_path), _REPO,
            ],
            env=env,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
        )
        for r in range(nproc)
    ]
    outs = []
    try:
        for p in procs:
            try:
                out, _ = p.communicate(timeout=600)
            except subprocess.TimeoutExpired:
                p.kill()
                out, _ = p.communicate()
            outs.append(out)
    finally:
        for p in procs:  # never orphan a hung peer rank
            if p.poll() is None:
                p.kill()
    for r, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {r} failed:\n{out}"
        assert f"rank {r}: FSDP-OK" in out


@requires_cpu_collectives
def pytest_two_process_composed_data_edge_mesh(tmp_path):
    """2-process composed (data x edge) mesh train step: each process
    owns one data row of a global (2, 4) mesh whose edge axis shards
    over its 4 local devices — the multi-process analog of the
    single-process composed coverage in ``dryrun_multichip`` and
    ``test_edge_sharded.pytest_dp_edge_composed_matches_data_parallel``.
    Losses and updated params must match a single-process composed
    reference on every rank."""
    port = _free_port()
    script = tmp_path / "composed_worker.py"
    script.write_text(_COMPOSED_WORKER)
    nproc = 2
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    procs = [
        subprocess.Popen(
            [
                sys.executable, str(script), str(r), str(nproc), str(port),
                str(tmp_path), _REPO,
            ],
            env=env,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
        )
        for r in range(nproc)
    ]
    outs = []
    try:
        for p in procs:
            try:
                out, _ = p.communicate(timeout=600)
            except subprocess.TimeoutExpired:
                p.kill()
                out, _ = p.communicate()
            outs.append(out)
    finally:
        for p in procs:  # never orphan a hung peer rank
            if p.poll() is None:
                p.kill()
    for r, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {r} failed:\n{out}"
        assert f"rank {r}: COMPOSED-OK" in out


@requires_cpu_collectives
def pytest_two_process_train_e2e(tmp_path):
    """True multi-host training: 2 OS processes × 2 CPU devices each, one
    global 4-device data mesh, full run_training + orbax checkpoint +
    run_prediction reload — the analog of the reference CI's e2e tests
    under ``mpirun -n 2`` (reference: .github/workflows/CI.yml)."""
    port = _free_port()
    script = tmp_path / "train_worker.py"
    script.write_text(_TRAIN_WORKER)
    nproc = 2
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    procs = [
        subprocess.Popen(
            [
                sys.executable, str(script), str(r), str(nproc), str(port),
                str(tmp_path), _REPO,
            ],
            env=env,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
        )
        for r in range(nproc)
    ]
    outs = []
    try:
        for p in procs:
            try:
                out, _ = p.communicate(timeout=900)
            except subprocess.TimeoutExpired:
                p.kill()
                out, _ = p.communicate()
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for r, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {r} failed:\n{out}"
        assert f"rank {r}: TRAIN-OK" in out


@requires_cpu_collectives
def pytest_two_process_distributed(tmp_path):
    port = _free_port()
    script = tmp_path / "worker.py"
    script.write_text(_WORKER)
    nproc = 2
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("XLA_FLAGS", None)
    procs = [
        subprocess.Popen(
            [
                sys.executable, str(script), str(r), str(nproc), str(port),
                str(tmp_path), _REPO,
            ],
            env=env,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
        )
        for r in range(nproc)
    ]
    outs = []
    try:
        for p in procs:
            try:
                out, _ = p.communicate(timeout=300)
            except subprocess.TimeoutExpired:
                p.kill()
                out, _ = p.communicate()
            outs.append(out)
    finally:
        for p in procs:  # never orphan a hung peer rank
            if p.poll() is None:
                p.kill()
    for r, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {r} failed:\n{out}"
        assert f"rank {r}: OK" in out


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port
