"""Data pipeline tests: generator round-trip, radius graph, normalization,
splitting, loader shapes. Mirrors the reference's unit-test strategy of a
deterministic dataset with known closed-form structure (reference:
tests/deterministic_graph_data.py, tests/test_periodic_boundary_conditions.py)."""

from collections import Counter

import numpy as np
import pytest

from hydragnn_tpu.data.synthetic import deterministic_graph_data, write_lsms_files
from hydragnn_tpu.data.lsms import read_lsms_dir
from hydragnn_tpu.data.radius_graph import radius_graph, radius_graph_pbc, edge_lengths
from hydragnn_tpu.data.ingest import prepare_dataset, build_edges
from hydragnn_tpu.data.loader import GraphLoader
from hydragnn_tpu.data.splitting import split_dataset
from hydragnn_tpu.utils.config import update_config


def base_config(multihead=True):
    voi = (
        {
            "input_node_features": [0],
            "output_names": ["sum_x_x2_x3", "x", "x2", "x3"],
            "output_index": [0, 0, 1, 2],
            "type": ["graph", "node", "node", "node"],
        }
        if multihead
        else {
            "input_node_features": [0],
            "output_names": ["sum_x_x2_x3"],
            "output_index": [0],
            "type": ["graph"],
        }
    )
    return {
        "Dataset": {
            "name": "unit_test",
            "format": "unit_test",
            "compositional_stratified_splitting": True,
            "rotational_invariance": False,
            "node_features": {
                "name": ["x", "x2", "x3"],
                "dim": [1, 1, 1],
                "column_index": [0, 6, 7],
            },
            "graph_features": {
                "name": ["sum_x_x2_x3"],
                "dim": [1],
                "column_index": [0],
            },
        },
        "NeuralNetwork": {
            "Architecture": {
                "model_type": "PNA",
                "radius": 2.0,
                "max_neighbours": 100,
                "periodic_boundary_conditions": False,
                "hidden_dim": 8,
                "num_conv_layers": 2,
                "output_heads": {
                    "graph": {
                        "num_sharedlayers": 2,
                        "dim_sharedlayers": 4,
                        "num_headlayers": 2,
                        "dim_headlayers": [10, 10],
                    },
                    "node": {"num_headlayers": 2, "dim_headlayers": [4, 4], "type": "mlp"},
                },
                "task_weights": [20.0, 1.0, 1.0, 1.0] if multihead else [1.0],
            },
            "Variables_of_interest": voi,
            "Training": {
                "num_epoch": 2,
                "perc_train": 0.7,
                "loss_function_type": "mse",
                "batch_size": 16,
                "Optimizer": {"type": "AdamW", "learning_rate": 0.01},
            },
        },
    }


def pytest_generator_lsms_roundtrip(tmp_path):
    mem = deterministic_graph_data(number_configurations=20, seed=11)
    write_lsms_files(str(tmp_path), number_configurations=20, seed=11)
    cfg = base_config()["Dataset"]
    disk = read_lsms_dir(str(tmp_path), cfg)
    # files sort lexically; match by configuration id
    order = sorted(range(20), key=lambda k: f"output{k}.txt")
    for file_pos, conf_id in enumerate(order):
        np.testing.assert_allclose(disk[file_pos].x, mem[conf_id].x, rtol=1e-6)
        np.testing.assert_allclose(disk[file_pos].pos, mem[conf_id].pos, rtol=1e-6)
        np.testing.assert_allclose(
            disk[file_pos].graph_y, mem[conf_id].graph_y, rtol=1e-6
        )


def pytest_radius_graph_simple():
    # 3 points on a line, spacing 1; r=1.5 connects neighbors only
    pos = np.array([[0.0, 0, 0], [1.0, 0, 0], [2.0, 0, 0]])
    ei = radius_graph(pos, 1.5)
    pairs = set(map(tuple, ei.T))
    assert pairs == {(0, 1), (1, 0), (1, 2), (2, 1)}
    lengths = edge_lengths(pos, ei)
    np.testing.assert_allclose(lengths, np.ones((4, 1)))


def pytest_radius_graph_max_neighbors():
    # hub with 4 spokes at increasing distance; cap keeps the 2 nearest
    pos = np.array(
        [[0.0, 0, 0], [1.0, 0, 0], [0, 1.1, 0], [0, 0, 1.2], [1.3, 0, 0]]
    )
    ei = radius_graph(pos, 2.0, max_num_neighbors=2)
    incoming0 = ei[0][ei[1] == 0]
    assert set(incoming0.tolist()) == {1, 2}


def pytest_radius_graph_brute_vs_celllist():
    rng = np.random.default_rng(0)
    pos = rng.uniform(0, 10, size=(300, 3))  # large enough for cell-list path
    r = 1.2
    ei = radius_graph(pos, r)
    # brute force reference
    diff = pos[:, None] - pos[None, :]
    dist = np.sqrt((diff**2).sum(-1))
    expect = {(j, i) for j in range(300) for i in range(300) if j != i and dist[j, i] <= r}
    assert set(map(tuple, ei.T)) == expect


def pytest_radius_graph_pbc_counts():
    # single atom in a unit cube with r=1: 6 face-neighbor images
    pos = np.zeros((1, 3))
    cell = np.eye(3)
    ei = radius_graph_pbc(pos, 1.0, cell)
    assert ei.shape[1] == 6
    # two atoms: H2-like pair, each sees the other plus its own images
    pos2 = np.array([[0.0, 0, 0], [0.5, 0, 0]])
    ei2 = radius_graph_pbc(pos2, 0.6, np.eye(3) * 1.0)
    # each atom: other atom at 0.5 in both x directions = 2 edges each way
    pairs = [tuple(e) for e in ei2.T]
    assert pairs.count((0, 1)) == 2 and pairs.count((1, 0)) == 2


def pytest_prepare_dataset_normalized_and_packed():
    config = base_config()
    samples = deterministic_graph_data(number_configurations=40, seed=5)
    train, val, test, mm_g, mm_n = prepare_dataset(samples, config)
    for split in (train, val, test):
        for s in split:
            assert 0.0 <= s.x.min() and s.x.max() <= 1.0
            assert s.edge_attr.max() <= 1.0 + 1e-6
            assert set(s.node_targets) == {"x", "x2", "x3"}
            assert set(s.graph_targets) == {"sum_x_x2_x3"}
            assert s.x.shape[1] == 1  # input selection applied


def pytest_update_config_inference():
    config = base_config()
    samples = deterministic_graph_data(number_configurations=40, seed=5)
    train, val, test, _, _ = prepare_dataset(samples, config)
    config = update_config(config, train, val, test)
    arch = config["NeuralNetwork"]["Architecture"]
    assert arch["output_dim"] == [1, 1, 1, 1]
    assert arch["output_type"] == ["graph", "node", "node", "node"]
    assert arch["input_dim"] == 1
    assert arch["max_neighbours"] > 0
    assert arch["pna_deg"] is not None and sum(arch["pna_deg"]) > 0
    assert arch["edge_dim"] is None  # no edge_features declared


def pytest_split_plain_proportions():
    samples = deterministic_graph_data(number_configurations=50, seed=1)
    tr, va, te = split_dataset(samples, 0.7, stratify_splitting=False)
    assert len(tr) == 35 and len(va) == 7 and len(te) == 8


def pytest_stratified_split_covers_categories():
    from hydragnn_tpu.data.splitting import composition_categories

    samples = deterministic_graph_data(number_configurations=200, seed=2)
    tr, va, te = split_dataset(samples, 0.7, stratify_splitting=True)
    cats_all = set(composition_categories(list(samples)))
    cats_train = set(composition_categories(tr))
    # every category with >=2 members must appear in train
    from collections import Counter

    counts = Counter(composition_categories(list(samples)))
    for c, n in counts.items():
        if n >= 2:
            assert c in cats_train


def pytest_loader_fixed_shapes_and_masks():
    config = base_config()
    samples = deterministic_graph_data(number_configurations=40, seed=5)
    train, _, _, _, _ = prepare_dataset(samples, config)
    loader = GraphLoader(train, batch_size=8, shuffle=True, seed=0)
    shapes = set()
    total_real = 0
    for epoch in range(2):
        loader.set_epoch(epoch)
        epoch_real = 0
        for b in loader:
            shapes.add((b.num_nodes, b.num_edges, b.num_graphs))
            epoch_real += int(np.asarray(b.graph_mask).sum())
        assert epoch_real == len(train)
    assert len(shapes) == 1  # one compiled shape for the whole run


def pytest_loader_device_stack():
    config = base_config()
    samples = deterministic_graph_data(number_configurations=40, seed=5)
    train, _, _, _, _ = prepare_dataset(samples, config)
    loader = GraphLoader(train, batch_size=8, device_stack=4)
    seen = 0
    for b in loader:
        assert b.nodes.ndim == 3 and b.nodes.shape[0] == 4
        seen += int(np.asarray(b.graph_mask).sum())
    assert seen == len(train)


def pytest_loader_sharding():
    samples = deterministic_graph_data(number_configurations=41, seed=5)
    build_edges(samples, radius=2.0, max_neighbours=100)
    l0 = GraphLoader(samples, batch_size=8, num_shards=2, shard_rank=0)
    l1 = GraphLoader(samples, batch_size=8, num_shards=2, shard_rank=1)
    # DistributedSampler-style equalization: both shards get ceil(41/2)=21
    # samples (one wraps around) so every host runs the same step count.
    assert l0.num_samples == 21 and l1.num_samples == 21
    assert len(l0) == len(l1) == 3
    assert (l0.pad_nodes, l0.pad_edges) == (l1.pad_nodes, l1.pad_edges)


def pytest_rotational_invariance():
    """Edge sets and edge lengths must be invariant under an arbitrary
    rigid rotation + translation when rotation normalization is applied
    (reference: tests/test_rotational_invariance.py:52-112 — float32 tol
    1e-4, float64 tol 1e-14)."""
    from hydragnn_tpu.data.dataset import GraphSample
    from hydragnn_tpu.data.ingest import normalize_rotation
    from hydragnn_tpu.data.radius_graph import edge_lengths, radius_graph

    rng = np.random.RandomState(13)
    n, radius = 24, 0.9

    def random_rotation():
        q, _ = np.linalg.qr(rng.normal(size=(3, 3)))
        if np.linalg.det(q) < 0:
            q[:, 0] = -q[:, 0]
        return q

    for dtype, tol in ((np.float32, 1e-4), (np.float64, 1e-14)):
        pos = rng.rand(n, 3).astype(dtype)
        rot = (random_rotation() @ pos.astype(np.float64).T).T + rng.normal(size=3)
        s_a = GraphSample(x=np.zeros((n, 1), np.float32), pos=pos.astype(dtype))
        s_b = GraphSample(x=np.zeros((n, 1), np.float32), pos=rot.astype(dtype))
        normalize_rotation([s_a, s_b])
        assert s_a.pos.dtype == dtype  # dtype preserved through normalization

        # normalization must actually ALIGN the copies: same canonical
        # coordinates per node, up to SVD's per-axis sign ambiguity (a
        # broken/no-op normalize_rotation would fail this even though
        # distances below are invariant under any rigid transform)
        pa = s_a.pos.astype(np.float64)
        pb = s_b.pos.astype(np.float64)
        for axis in range(3):
            col_a, col_b = pa[:, axis], pb[:, axis]
            err = min(np.abs(col_a - col_b).max(), np.abs(col_a + col_b).max())
            # coordinates accumulate a few ulps more SVD round-off than
            # the derived edge lengths the reference bounds at `tol`;
            # broken normalization errs at O(1), far above 100x tol
            assert err < 100 * tol, f"axis {axis} not aligned ({dtype}): {err}"

        ei_a = radius_graph(pa, radius)
        ei_b = radius_graph(pb, radius)
        set_a = {(int(u), int(v)) for u, v in ei_a.T}
        set_b = {(int(u), int(v)) for u, v in ei_b.T}
        assert set_a == set_b, f"edge sets differ under rotation ({dtype})"

        # edge lengths in full float64 (the helper casts to f32, which
        # would make the 1e-14 band vacuous)
        len_a = np.sort(np.linalg.norm(pa[ei_a[0]] - pa[ei_a[1]], axis=1))
        len_b = np.sort(np.linalg.norm(pb[ei_b[0]] - pb[ei_b[1]], axis=1))
        np.testing.assert_allclose(len_a, len_b, rtol=tol, atol=tol)


def pytest_rotation_keeps_dimensions_for_tiny_graphs():
    """Graphs with fewer than 3 nodes must keep 3-D positions through
    rotation normalization (regression: reduced SVD projected a 2-node
    graph down to 2-D and broke the in-place write)."""
    from hydragnn_tpu.data.dataset import GraphSample
    from hydragnn_tpu.data.ingest import normalize_rotation

    for n in (1, 2):
        s = GraphSample(
            x=np.zeros((n, 1), np.float32),
            pos=np.arange(3 * n, dtype=np.float32).reshape(n, 3),
        )
        normalize_rotation([s])
        assert s.pos.shape == (n, 3)
        assert np.isfinite(s.pos).all()


def pytest_periodic_bcc_supercell():
    """5x5x5 BCC Cr supercell (a=3.6, radius=5.0): every atom must see
    exactly its 8 first-shell + 6 second-shell periodic neighbors — 14
    without self-loops, 15 with (reference:
    tests/test_periodic_boundary_conditions.py pytest_periodic_bcc_large,
    built there with ase.build; constructed directly here)."""
    a, reps, radius = 3.6, 5, 5.0
    basis = np.array([[0.0, 0.0, 0.0], [a / 2, a / 2, a / 2]])
    shifts = np.array(
        [[i, j, k] for i in range(reps) for j in range(reps) for k in range(reps)]
    ) * a
    pos = (basis[None, :, :] + shifts[:, None, :]).reshape(-1, 3)
    cell = np.eye(3) * (reps * a)
    n = pos.shape[0]
    assert n == 250

    ei = radius_graph_pbc(pos, radius, cell, loop=False)
    assert ei.shape[1] == 14 * n, ei.shape
    ei_loops = radius_graph_pbc(pos, radius, cell, loop=True)
    assert ei_loops.shape[1] == 15 * n, ei_loops.shape


def pytest_stratified_subsample():
    """Variables_of_interest.subsample_percentage downselects with
    composition stratification (reference: stratified_sampling,
    abstractrawdataset.py:412-452): ~the requested fraction overall,
    every multi-member category still represented."""
    from hydragnn_tpu.data.splitting import (
        stratified_subsample,
        subsample_categories,
    )

    samples = deterministic_graph_data(number_configurations=200, seed=2)
    sub = stratified_subsample(list(samples), 0.3)
    assert 0.2 * len(samples) <= len(sub) <= 0.45 * len(samples)
    cats_all = Counter(subsample_categories(list(samples)))
    cats_sub = set(subsample_categories(sub))
    # floor allocation guarantees representation once frac * n >= 1
    for c, n in cats_all.items():
        if 0.3 * n >= 1:
            assert c in cats_sub

    with pytest.raises(ValueError):
        stratified_subsample(list(samples), 0.0)
    assert len(stratified_subsample(list(samples), 1.0)) == len(samples)


def pytest_subsample_through_prepare_dataset():
    config = base_config()
    config["NeuralNetwork"]["Variables_of_interest"]["subsample_percentage"] = 0.5
    # plain split: the stratified splitter would re-inflate the count by
    # duplicating singleton categories (its own reference-parity behavior)
    config["Dataset"]["compositional_stratified_splitting"] = False
    samples = deterministic_graph_data(number_configurations=100, seed=5)
    train, val, test, _, _ = prepare_dataset(samples, config)
    assert len(train) + len(val) + len(test) == 50


def pytest_point_pair_features():
    """PointPairFeatures descriptor (reference usage:
    abstractrawdataset.py:380-383; PyG transform semantics): 4 extra
    edge-attr columns [rho_norm, angle(n_i,d), angle(n_j,d),
    angle(n_i,n_j)], rotation-invariant, requiring meta['norm']."""
    from hydragnn_tpu.data.ingest import build_edges

    samples = deterministic_graph_data(number_configurations=6, seed=3)
    for s in samples:
        rng = np.random.default_rng(s.num_nodes)
        n = rng.normal(size=(s.num_nodes, 3))
        s.meta["norm"] = n / np.linalg.norm(n, axis=1, keepdims=True)
    build_edges(samples, radius=2.0, max_neighbours=100, point_pair_features=True)
    for s in samples:
        assert s.edge_attr.shape[1] == 5  # length + 4 PPF columns
        ppf = s.edge_attr[:, 1:]
        assert (ppf[:, 0] >= 0).all() and (ppf[:, 0] <= 1.0 + 1e-6).all()
        # angles in [0, pi]
        assert (ppf[:, 1:] >= 0).all() and (ppf[:, 1:] <= np.pi + 1e-6).all()
        # angle(n_i, n_j) symmetric in edge direction: the reversed edge
        # (present in an undirected radius graph) has the same value
        fwd = {(int(a), int(b)): v for a, b, v in zip(*s.edge_index, ppf[:, 3])}
        for (a, b), v in fwd.items():
            assert abs(fwd[(b, a)] - v) < 1e-5

    # missing normals is a clear error, not a crash downstream
    bad = deterministic_graph_data(number_configurations=2, seed=3)
    with pytest.raises(ValueError, match="norm"):
        build_edges(bad, radius=2.0, max_neighbours=100, point_pair_features=True)


def pytest_descriptors_grow_edge_dim():
    config = base_config()
    config["NeuralNetwork"]["Architecture"]["model_type"] = "PNA"
    config["NeuralNetwork"]["Architecture"]["edge_features"] = ["lengths"]
    config["Dataset"]["Descriptors"] = {
        "SphericalCoordinates": True,
        "PointPairFeatures": True,
    }
    samples = deterministic_graph_data(number_configurations=30, seed=5)
    for s in samples:
        s.meta["norm"] = np.ones((s.num_nodes, 3), dtype=np.float32) / np.sqrt(3.0)
    train, val, test, _, _ = prepare_dataset(samples, config)
    config = update_config(config, train, val, test)
    assert config["NeuralNetwork"]["Architecture"]["edge_dim"] == 1 + 2 + 4
    for s in train:
        assert s.edge_attr.shape[1] == 1 + 2 + 4

    # the model consumes the widened edge attributes end-to-end
    from hydragnn_tpu.models.create import create_model_config

    loader = GraphLoader(train, 8)
    example = next(iter(loader))
    model, variables = create_model_config(config["NeuralNetwork"], example)
    outputs = model.apply(variables, example, train=False)
    assert all(np.isfinite(np.asarray(o)).all() for o in outputs)

    # descriptors without edge_features: loud config error
    config2 = base_config()
    config2["Dataset"]["Descriptors"] = {"SphericalCoordinates": True}
    samples2 = deterministic_graph_data(number_configurations=30, seed=5)
    train2, val2, test2, _, _ = prepare_dataset(samples2, config2)
    with pytest.raises(ValueError, match="edge_features"):
        update_config(config2, train2, val2, test2)
