"""Unit tests for masked segment ops against hand-computed aggregations."""

import numpy as np
import jax.numpy as jnp
import pytest

from hydragnn_tpu.graph import segment as S


IDS = jnp.array([0, 0, 1, 2, 2, 2], dtype=jnp.int32)
DATA = jnp.array([1.0, 3.0, 5.0, 2.0, 4.0, 6.0])
MASK = jnp.array([True, True, True, True, False, True])
NSEG = 4  # segment 3 is empty


def test_segment_sum():
    out = S.segment_sum(DATA, IDS, NSEG)
    np.testing.assert_allclose(out, [4.0, 5.0, 12.0, 0.0])


def test_segment_sum_masked():
    out = S.segment_sum(DATA, IDS, NSEG, mask=MASK)
    np.testing.assert_allclose(out, [4.0, 5.0, 8.0, 0.0])


def test_segment_mean():
    out = S.segment_mean(DATA, IDS, NSEG, mask=MASK)
    np.testing.assert_allclose(out, [2.0, 5.0, 4.0, 0.0])


def test_segment_max_min_empty_safe():
    out_max = S.segment_max(DATA, IDS, NSEG, mask=MASK)
    out_min = S.segment_min(DATA, IDS, NSEG, mask=MASK)
    np.testing.assert_allclose(out_max, [3.0, 5.0, 6.0, 0.0])
    np.testing.assert_allclose(out_min, [1.0, 5.0, 2.0, 0.0])


def test_segment_std_matches_biased_formula():
    out = S.segment_std(DATA, IDS, NSEG, eps=0.0)
    # segment 0: mean 2, mean_sq 5 -> std 1
    np.testing.assert_allclose(out[0], 1.0, atol=1e-6)
    np.testing.assert_allclose(out[1], 0.0, atol=1e-3)


def test_segment_softmax_sums_to_one():
    p = S.segment_softmax(DATA, IDS, NSEG, mask=MASK)
    sums = S.segment_sum(p, IDS, NSEG)
    np.testing.assert_allclose(sums[:3], 1.0, atol=1e-6)
    assert float(p[4]) == 0.0  # masked edge gets zero probability
    np.testing.assert_allclose(sums[3], 0.0)  # empty segment


def test_segment_2d_features():
    data = jnp.stack([DATA, 2 * DATA], axis=1)
    out = S.segment_sum(data, IDS, NSEG, mask=MASK)
    np.testing.assert_allclose(out[:, 1], 2 * out[:, 0])


def test_node_degree():
    deg = S.node_degree(IDS, NSEG, mask=MASK)
    np.testing.assert_allclose(deg, [2.0, 1.0, 2.0, 0.0])
