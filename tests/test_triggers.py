"""Incident-grade tracing tests: trace IDs + spans + Chrome export
(hydragnn_tpu/obs/trace.py), SLO trigger rules + rate limiting +
overhead budget (hydragnn_tpu/obs/triggers.py), incident bundle
round-trip and crashed-mid-write tolerance, the spans/profiler
suppression contract, and the queue gauges the serve SLO rules read."""

import json
import os

import pytest

from hydragnn_tpu.obs.flight import FlightRecorder, read_flight_record
from hydragnn_tpu.obs.registry import MetricsRegistry
from hydragnn_tpu.obs.trace import (
    RequestTrace,
    Tracer,
    flight_to_chrome,
    new_trace_id,
)
from hydragnn_tpu.obs.triggers import (
    RULE_KINDS,
    IncidentRecorder,
    TriggerEngine,
    TriggerRule,
    TriggerVerdict,
    list_incidents,
    validate_incident_bundle,
    validate_incident_manifest,
)


def _verdict(rule="r", kind="loss_spike", metric="train_loss"):
    return TriggerVerdict(rule, kind, metric, 9.0, 3.0, 1234.5)


class FakeClock:
    def __init__(self, t=100.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


@pytest.fixture
def no_capture(monkeypatch):
    """Stub the jax.profiler capture so trigger tests stay hermetic
    (one real-capture test exercises the true path)."""
    from hydragnn_tpu.utils import profile

    started = []
    monkeypatch.setattr(
        profile, "try_start_capture", lambda prefix: started.append(prefix) or True
    )
    monkeypatch.setattr(profile, "stop_capture", lambda: None)
    return started


# ---------------------------------------------------------------------------
# traces
# ---------------------------------------------------------------------------


def test_trace_ids_unique_and_greppable():
    ids = {new_trace_id() for _ in range(64)}
    assert len(ids) == 64
    assert all(len(i) == 16 for i in ids)


def test_request_trace_marks_and_spans():
    tr = RequestTrace("abc", seq=7)
    tr.mark("route", bucket=1)
    tr.add_span("execute", tr.t_admit, tr.t_admit + 0.25, occupancy=4)
    assert [s["name"] for s in tr.spans] == ["route", "execute"]
    assert tr.spans[1]["dur_ms"] == pytest.approx(250.0)
    assert tr.spans[1]["occupancy"] == 4
    d = tr.to_dict()
    assert d["trace_id"] == "abc" and d["seq"] == 7 and len(d["spans"]) == 2


def test_tracer_disabled_returns_none():
    t = Tracer(enabled=False)
    assert t.begin(seq=0) is None
    t.finish(None)  # null-guarded: the off path must not throw
    assert t.finished_count == 0


def test_tracer_samples_first_and_every_nth_into_flight(tmp_path):
    path = str(tmp_path / "flight.jsonl")
    with FlightRecorder(path) as fr:
        fr.start_run({"run": "t"})
        tracer = Tracer(flight=fr, enabled=True, sample_every=3)
        for i in range(7):
            tr = tracer.begin(seq=i)
            tr.mark("serve.queue_wait")
            tracer.finish(tr)
        fr.end_run(status="stopped")
    captures = [
        e for e in read_flight_record(path) if e["kind"] == "trace_capture"
    ]
    # traces 0, 3, 6 sampled (first always) — schema-complete events
    assert [e["seq"] for e in captures] == [0, 3, 6]
    assert all(e["trace_id"] and e["spans"] for e in captures)


def test_tracer_chrome_export_and_flight_to_chrome(tmp_path):
    path = str(tmp_path / "flight.jsonl")
    with FlightRecorder(path) as fr:
        fr.start_run({"run": "demo"})
        tracer = Tracer(flight=fr, enabled=True, sample_every=1)
        tr = tracer.begin(seq=0)
        tr.mark("serve.queue_wait")
        tr.mark("serve.device_execute", bucket=2)
        tracer.finish(tr)
        fr.epoch(0, train_loss=1.0, val_loss=1.1, time=2.5)
        fr.end_run(status="completed")

    out = str(tmp_path / "trace.json")
    tracer.export_chrome(out)
    with open(out) as f:
        chrome = json.load(f)
    names = [e["name"] for e in chrome["traceEvents"]]
    assert "serve.queue_wait" in names and "serve.device_execute" in names
    assert all(e["ph"] == "X" for e in chrome["traceEvents"])

    # offline join: flight JSONL alone -> one timeline with the epoch
    joined = flight_to_chrome(path)
    names = [e["name"] for e in joined["traceEvents"]]
    assert "serve.device_execute" in names and "epoch 0" in names
    epoch_ev = next(e for e in joined["traceEvents"] if e["name"] == "epoch 0")
    assert epoch_ev["dur"] == pytest.approx(2.5e6)
    assert epoch_ev["args"]["run"] == "demo"


# ---------------------------------------------------------------------------
# trigger rules: each fires on its signal, none on a clean run
# ---------------------------------------------------------------------------


def _engine(rules, registry=None, **kw):
    kw.setdefault("cooldown_s", 0.0)
    kw.setdefault("max_incidents", 100)
    return TriggerEngine(rules, registry=registry or MetricsRegistry(), **kw)


def test_latency_p99_rule_fires_only_over_target():
    r = MetricsRegistry()
    eng = _engine([TriggerRule("p99", "latency_p99", "serve.latency_s", 0.5)], r)
    h = r.histogram("serve.latency_s")
    for _ in range(20):
        h.observe(0.01)
    assert eng.evaluate() == []  # clean: p99 well under target
    for _ in range(20):
        h.observe(2.0)
    (v,) = eng.evaluate()
    assert v.rule == "p99" and v.observed > 0.5 and not v.injected


def test_queue_depth_and_age_rules():
    r = MetricsRegistry()
    eng = _engine(
        [
            TriggerRule("qd", "queue_depth", "serve.queue_depth", 10),
            TriggerRule("qa", "queue_age", "serve.queue_oldest_age_s", 1.0),
        ],
        r,
    )
    r.gauge("serve.queue_depth").set(3)
    r.gauge("serve.queue_oldest_age_s").set(0.2)
    assert eng.evaluate() == []
    r.gauge("serve.queue_depth").set(25)
    (v,) = eng.evaluate()
    assert v.rule == "qd" and v.observed == 25
    r.gauge("serve.queue_depth").set(3)
    r.gauge("serve.queue_oldest_age_s").set(4.5)
    (v,) = eng.evaluate()
    assert v.rule == "qa"


def test_nonfinite_burst_rule_uses_counter_delta():
    r = MetricsRegistry()
    eng = _engine(
        [TriggerRule("nf", "nonfinite_burst", "train.nonfinite_skipped", 2)], r
    )
    c = r.counter("train.nonfinite_skipped")
    assert eng.evaluate() == []  # zero delta
    c.inc(1)
    assert eng.evaluate() == []  # delta 1 < 2
    c.inc(3)
    (v,) = eng.evaluate()
    assert v.rule == "nf" and v.observed == 3  # delta since last evaluate
    assert eng.evaluate() == []  # delta resets


def test_loss_spike_and_mfu_drop_rolling_median_rules():
    eng = _engine(
        [
            TriggerRule("spike", "loss_spike", "train_loss", 3.0),
            TriggerRule("mfu", "mfu_drop", "mfu", 0.5),
        ]
    )
    for loss, mfu in ((1.0, 0.4), (0.9, 0.41), (0.8, 0.39)):
        eng.observe("train_loss", loss)
        eng.observe("mfu", mfu)
        assert eng.evaluate() == []  # a healthy declining run
    eng.observe("train_loss", 5.0)  # > 3x median(1.0, 0.9, 0.8)
    eng.observe("mfu", 0.4)
    (v,) = eng.evaluate()
    assert v.rule == "spike" and v.detail["rolling_median"] == pytest.approx(0.9)
    eng.observe("train_loss", 0.7)
    eng.observe("mfu", 0.05)  # < 0.5x median
    (v,) = eng.evaluate()
    assert v.rule == "mfu"


def test_observe_drops_none_samples():
    eng = _engine([TriggerRule("mfu", "mfu_drop", "mfu", 0.5)])
    for _ in range(5):
        eng.observe("mfu", None)  # MFU unavailable off-TPU
    assert eng.evaluate() == []


def test_injected_trigger_force_fires_once(monkeypatch):
    monkeypatch.setenv("HYDRAGNN_INJECT_TRIGGER", "forced_rule")
    from hydragnn_tpu.resilience import inject

    monkeypatch.setattr(inject, "_TRIGGER_FIRED", False)
    other = _engine([TriggerRule("other", "loss_spike", "x", 3.0)])
    assert other.evaluate() == []  # unknown rule name: NOT consumed
    eng = _engine([TriggerRule("forced_rule", "loss_spike", "train_loss", 3.0)])
    (v,) = eng.evaluate()
    assert v.injected and v.rule == "forced_rule"
    assert eng.evaluate() == []  # one-shot


# ---------------------------------------------------------------------------
# rate limiting + overhead budget
# ---------------------------------------------------------------------------


def test_engine_admits_at_most_one_verdict_per_evaluate():
    r = MetricsRegistry()
    eng = _engine(
        [
            TriggerRule("qd", "queue_depth", "serve.queue_depth", 1),
            TriggerRule("qa", "queue_age", "serve.queue_oldest_age_s", 0.1),
        ],
        r,
    )
    r.gauge("serve.queue_depth").set(10)
    r.gauge("serve.queue_oldest_age_s").set(10.0)
    admitted = eng.evaluate()
    assert len(admitted) == 1 and eng.suppressed == 1


def test_engine_cooldown_and_max_incidents():
    clock = FakeClock()
    r = MetricsRegistry()
    eng = TriggerEngine(
        [TriggerRule("qd", "queue_depth", "serve.queue_depth", 1)],
        registry=r,
        cooldown_s=60.0,
        max_incidents=2,
        clock=clock,
    )
    r.gauge("serve.queue_depth").set(10)
    assert len(eng.evaluate()) == 1
    assert eng.evaluate() == []  # inside cooldown
    clock.advance(61)
    assert len(eng.evaluate()) == 1
    clock.advance(61)
    assert eng.evaluate() == []  # max_incidents reached
    s = eng.summary()
    assert s["fired"] == 2 and s["suppressed"] == 2
    assert s["incidents"] == ["qd", "qd"]
    assert 0.0 <= s["overhead_frac"] < 1.0


def test_recorder_overhead_budget_suppresses_new_incidents(tmp_path, no_capture):
    clock = FakeClock()
    rec = IncidentRecorder(
        str(tmp_path / "incidents"),
        profile_steps=1000,
        profile_s=30.0,
        overhead_frac=0.05,
        clock=clock,
    )
    clock.advance(10.0)
    # the FIRST capture is always admitted (zero spent so far) — a short
    # CI run must still capture its one planned incident
    inc = rec.open_incident(_verdict())
    assert inc is not None
    rec.tick()  # starts the capture clock
    clock.advance(31.0)
    rec.tick()  # ...the 30s wall bound trips
    assert rec.open is None
    assert rec.capture_s == pytest.approx(31.0)
    # spent 31s of capture in ~41s of run: way over the 5% budget
    assert rec.open_incident(_verdict("second")) is None
    assert rec.suppressed_budget == 1
    clock.advance(10_000.0)  # 31s / 10ks ~ 0.3% — budget recovered
    assert rec.open_incident(_verdict("third")) is not None


def test_recorder_keeps_one_incident_open(tmp_path, no_capture):
    clock = FakeClock()
    rec = IncidentRecorder(
        str(tmp_path / "i"), profile_steps=3, profile_s=999.0,
        overhead_frac=1.0, clock=clock,
    )
    inc = rec.open_incident(_verdict())
    assert inc is not None
    assert rec.open_incident(_verdict("other")) is None  # one at a time
    for _ in range(3):
        rec.tick()
    assert rec.open is None and rec.closed_ids == [inc.id]
    assert rec.open_incident(_verdict("other")) is not None  # slot free


def test_incident_capture_bounded_by_wall_time(tmp_path, no_capture):
    clock = FakeClock()
    rec = IncidentRecorder(
        str(tmp_path / "i"), profile_steps=10_000, profile_s=5.0,
        overhead_frac=1.0, clock=clock,
    )
    rec.open_incident(_verdict())
    rec.tick()
    assert rec.open is not None
    clock.advance(6.0)  # wall bound trips before the step bound
    rec.tick()
    assert rec.open is None


# ---------------------------------------------------------------------------
# incident bundles
# ---------------------------------------------------------------------------


def test_incident_bundle_round_trip(tmp_path, no_capture):
    root = str(tmp_path / "incidents")
    flight_path = str(tmp_path / "flight.jsonl")
    with FlightRecorder(flight_path) as fr:
        fr.start_run({"run": "t"})
        reg = MetricsRegistry()
        reg.counter("train.nonfinite_skipped").inc(4)
        rec = IncidentRecorder(
            root, registry=reg, flight_path=flight_path,
            profile_steps=2, profile_s=999.0, overhead_frac=1.0,
        )
        inc = rec.open_incident(_verdict("nf", "nonfinite_burst"), flight=fr)
        for _ in range(2):
            rec.tick()
        fr.end_run(status="completed")

    (bundle,) = list_incidents(root)
    assert validate_incident_bundle(bundle) == []
    with open(os.path.join(bundle, "incident_manifest.json")) as f:
        man = json.load(f)
    assert man["rule"] == "nf" and man["status"] == "ok"
    assert man["trigger"]["observed"] == 9.0
    assert man["profile"]["steps"] == 2
    assert validate_incident_manifest(man) == []
    # every sidecar the manifest names exists and parses
    with open(os.path.join(bundle, "metrics.json")) as f:
        assert json.load(f)["train"]["nonfinite_skipped"] == 4
    with open(os.path.join(bundle, "flight_tail.jsonl")) as f:
        tail = [json.loads(line) for line in f if line.strip()]
    # the tail snapshots the record as of OPEN (before the incident
    # pointer event lands), so run_start is there
    assert inc is not None
    assert any(e["kind"] == "run_start" for e in tail)
    # the flight pointer was recorded at OPEN
    evs = read_flight_record(flight_path)
    assert any(e["kind"] == "incident" and e["path"] == bundle for e in evs)


def test_incident_finalize_marks_truncated(tmp_path, no_capture):
    rec = IncidentRecorder(
        str(tmp_path / "i"), profile_steps=100, profile_s=999.0,
        overhead_frac=1.0,
    )
    rec.open_incident(_verdict())
    rec.tick()
    rec.finalize()  # run ends mid-capture
    (bundle,) = list_incidents(str(tmp_path / "i"))
    with open(os.path.join(bundle, "incident_manifest.json")) as f:
        assert json.load(f)["status"] == "truncated"
    assert validate_incident_bundle(bundle) == []


def test_readers_tolerate_crash_mid_incident_write(tmp_path, no_capture):
    """A run that dies between sidecars and manifest leaves a bundle
    with NO manifest and a flight record with a TRUNCATED tail line;
    both must stay readable."""
    root = str(tmp_path / "incidents")
    flight_path = str(tmp_path / "flight.jsonl")
    with FlightRecorder(flight_path) as fr:
        fr.start_run({"run": "t"})
        rec = IncidentRecorder(
            root, flight_path=flight_path, profile_steps=5,
            profile_s=999.0, overhead_frac=1.0,
        )
        rec.open_incident(_verdict(), flight=fr)
        # crash here: no ticks, no close, no end_run
    with open(flight_path, "a") as f:
        f.write('{"v": 2, "kind": "incident", "id": "i002-half')  # torn write

    (bundle,) = list_incidents(root)
    problems = validate_incident_bundle(bundle)
    assert problems and "manifest missing" in problems[0]
    # sidecars written at open are intact
    assert os.path.exists(os.path.join(bundle, "trigger.json"))
    # the reader DROPS the torn tail line (the expected crash shape)
    # instead of raising; the incident pointer survives as the last
    # parseable event
    events = read_flight_record(flight_path)
    assert events[-1]["kind"] == "incident"

    # the renderer narrates the crashed bundle instead of exploding
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "_t_incident_report",
        os.path.join(os.path.dirname(__file__), "..", "tools", "incident_report.py"),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    text = mod.render_bundle(bundle)
    assert "NO MANIFEST" in text and "trigger.json" in text


def test_incident_manifest_schema_rejects_malformed():
    assert validate_incident_manifest([]) != []
    assert any(
        "missing required field" in p for p in validate_incident_manifest({})
    )
    good = {
        "schema_version": 1,
        "id": "i001-r",
        "rule": "r",
        "kind": "loss_spike",
        "status": "ok",
        "trigger": {"rule": "r", "kind": "loss_spike", "observed": 1.0,
                    "threshold": 3.0},
        "files": {},
        "profile": {"captured": False, "steps": 0, "duration_s": 0.0,
                    "nonempty": False},
    }
    assert validate_incident_manifest(good) == []
    bad = dict(good, kind="not_a_kind")
    assert any("unknown rule kind" in p for p in validate_incident_manifest(bad))


def test_lint_schema_mirrors_runtime_rule_kinds(tmp_path):
    """graftlint --artifacts must stay jax-free, so lint/artifacts.py
    carries its own copy of the manifest schema; pin the two against
    drift."""
    from hydragnn_tpu.lint.artifacts import (
        _INCIDENT_RULE_KINDS,
        validate_artifacts,
    )

    assert tuple(_INCIDENT_RULE_KINDS) == tuple(RULE_KINDS)
    good = {
        "schema_version": 1,
        "id": "i001-r",
        "rule": "r",
        "kind": "latency_p99",
        "status": "ok",
        "trigger": {"rule": "r", "kind": "latency_p99", "observed": 1.0,
                    "threshold": 0.5},
        "files": {},
        "profile": {"captured": True, "steps": 3, "duration_s": 1.0,
                    "nonempty": False},
    }
    path = tmp_path / "incident_manifest.json"
    path.write_text(json.dumps(good))
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    assert validate_artifacts(repo_root, [str(path)]) == []
    path.write_text(json.dumps(dict(good, profile={})))
    findings = validate_artifacts(repo_root, [str(path)])
    assert findings and all(f.rule == "HGART" for f in findings)


def test_rule_kind_validation():
    with pytest.raises(ValueError):
        TriggerRule("x", "not_a_kind", "m", 1.0)


# ---------------------------------------------------------------------------
# real capture + spans suppression
# ---------------------------------------------------------------------------


def test_incident_real_profiler_capture(tmp_path):
    """The true jax.profiler path: one bounded capture lands real trace
    files in the bundle's profile/ dir and the manifest says so."""
    import jax

    rec = IncidentRecorder(
        str(tmp_path / "i"), profile_steps=2, profile_s=999.0,
        overhead_frac=1.0,
    )
    rec.open_incident(_verdict())
    for _ in range(2):
        jax.block_until_ready(jax.numpy.ones((8, 8)) @ jax.numpy.ones((8, 8)))
        rec.tick()
    (bundle,) = list_incidents(str(tmp_path / "i"))
    assert validate_incident_bundle(bundle) == []
    with open(os.path.join(bundle, "incident_manifest.json")) as f:
        man = json.load(f)
    assert man["profile"]["captured"] is True
    assert man["profile"]["nonempty"] is True


def test_spans_sampling_suppressed_while_capture_active(monkeypatch):
    """Satellite pin: StepSpans' sampled block_until_ready window must
    NOT fire while a profiler capture is live — the sync fence would
    serialize the very steps being profiled."""
    from hydragnn_tpu.obs.spans import StepSpans
    from hydragnn_tpu.utils import profile

    spans = StepSpans(sample_steps=2, skip_first=0)
    spans.epoch_start(0)
    monkeypatch.setattr(profile, "capture_active", lambda: True)
    for _ in range(3):
        spans.step(lambda: 1.0)
    assert spans.sampled == 0  # every sample skipped outright
    assert spans.steps == 3  # the step index still advanced

    spans.epoch_start(1)
    monkeypatch.setattr(profile, "capture_active", lambda: False)
    for _ in range(3):
        spans.step(lambda: 1.0)
    assert spans.sampled == 2  # normal sampling resumes


def test_capture_slot_is_exclusive(tmp_path, monkeypatch):
    """utils/profile.py owns the ONE process-wide jax trace slot:
    a second start is refused, not raised."""
    from hydragnn_tpu.utils import profile

    calls = []
    monkeypatch.setattr(
        profile.jax.profiler, "start_trace", lambda p: calls.append(p)
    )
    monkeypatch.setattr(profile.jax.profiler, "stop_trace", lambda: None)
    assert profile.try_start_capture(str(tmp_path / "a"))
    assert profile.capture_active()
    assert not profile.try_start_capture(str(tmp_path / "b"))  # refused
    profile.stop_capture()
    assert not profile.capture_active()
    assert profile.try_start_capture(str(tmp_path / "c"))
    profile.stop_capture()
    assert calls == [str(tmp_path / "a"), str(tmp_path / "c")]


# ---------------------------------------------------------------------------
# queue gauges (the serve SLO rules' inputs)
# ---------------------------------------------------------------------------


def test_batcher_oldest_age_tracks_head_request():
    from hydragnn_tpu.serve.batcher import MicroBatchQueue

    q = MicroBatchQueue(num_buckets=2, max_batch=4, max_delay_s=60.0,
                        max_pending=16)
    assert q.oldest_age_s() == 0.0
    q.put(0, "a", seq=0)
    import time as _time

    _time.sleep(0.01)
    q.put(1, "b", seq=1)
    assert q.oldest_age_s() >= 0.01  # head of bucket 0 is the oldest
    q.cancel_pending()
    assert q.oldest_age_s() == 0.0


def test_queue_gauges_reach_prometheus_text():
    from hydragnn_tpu.serve.metrics import ServeMetrics

    m = ServeMetrics(num_buckets=1, registry=MetricsRegistry())
    m.set_queue_depth(5, oldest_age_s=1.25)
    snap = m.snapshot()
    assert snap["queue_depth"] == 5
    assert snap["queue_oldest_age_s"] == 1.25
    text = m.to_prometheus_text()
    assert 'hydragnn_serve_queue_depth{rank="0"} 5' in text
    assert 'hydragnn_serve_queue_oldest_age_s{rank="0"} 1.25' in text
