"""Round-trip tests for the reference ADIOS2-format importer.

The fixture mirrors AdiosWriter.save's on-disk schema EXACTLY
(reference: hydragnn/utils/adiosdataset.py:79-179, single rank): per
split, concatenated per-key global arrays along the writer's inferred
ragged axis plus variable_count/variable_offset index arrays and the
ndata/keys/variable_dim attributes. The adios2 LIBRARY (absent in this
image) is mocked at the exact API surface both the reader and the
standalone export script consume (FileReader: read / read_attribute /
read_attribute_string / available_attributes) — so these tests pin the
schema math (slicing, vdim, offsets) and the end-to-end conversion,
while the real-BP byte decoding is adios2's own job in environments
that have it."""

import os
import pickle
import sys
import types

import numpy as np
import pytest

from hydragnn_tpu.data.adios_reference import (
    ReferenceAdiosReader,
    import_adios_dataset,
    looks_like_adios,
)
from hydragnn_tpu.data.container import ContainerDataset


def _writer_schema(samples, label):
    """Mirror AdiosWriter.save (single rank): returns (vars, attrs).

    ``samples``: list of {key: ndarray} dicts. The ragged axis per key
    follows the writer's rule: the ONE axis where sample shapes differ,
    else axis 1 (adiosdataset.py:103-107)."""
    variables: dict = {}
    attrs: dict = {}
    keys = sorted(samples[0].keys())
    attrs[f"{label}/ndata"] = np.array(len(samples))
    attrs[f"{label}/keys"] = list(keys)
    for k in keys:
        arr_list = [np.asarray(s[k]) for s in samples]
        m0 = np.min([x.shape for x in arr_list], axis=0)
        m1 = np.max([x.shape for x in arr_list], axis=0)
        wh = np.where(m0 != m1)[0]
        assert len(wh) < 2
        vdim = int(wh[0]) if len(wh) == 1 else 1
        variables[f"{label}/{k}"] = np.concatenate(arr_list, axis=vdim)
        vcount = np.array([x.shape[vdim] for x in arr_list])
        voffset = np.zeros_like(vcount)
        voffset[1:] = np.cumsum(vcount)[:-1]
        variables[f"{label}/{k}/variable_count"] = vcount
        variables[f"{label}/{k}/variable_offset"] = voffset
        attrs[f"{label}/{k}/variable_dim"] = np.array(vdim)
    attrs["total_ndata"] = np.array(len(samples))
    return variables, attrs


_FAKE_FILES: dict = {}


def _install_fake_adios2(monkeypatch):
    """Register a minimal adios2 module exposing the 2.9+ FileReader
    surface the importer (and export script) consume."""

    mod = types.ModuleType("adios2")

    class FileReader:
        def __init__(self, filename):
            if filename not in _FAKE_FILES:
                raise FileNotFoundError(filename)
            self._vars, self._attrs = _FAKE_FILES[filename]
            self._closed = False

        def close(self):
            self._closed = True

        def available_attributes(self):
            return {name: {"Type": "fake"} for name in self._attrs}

        def available_variables(self):
            return {name: {"Type": "fake"} for name in self._vars}

        def read(self, name):
            assert not self._closed
            return self._vars[name]

        def read_attribute(self, name):
            assert not self._closed
            return np.asarray(self._attrs[name])

        def read_attribute_string(self, name):
            assert not self._closed
            v = self._attrs[name]
            assert isinstance(v, list)
            return list(v)

    mod.FileReader = FileReader
    monkeypatch.setitem(sys.modules, "adios2", mod)


def _make_truth(n_samples, seed=11):
    rng = np.random.default_rng(seed)
    samples, truth = [], []
    for _ in range(n_samples):
        n = int(rng.integers(3, 7))
        x = rng.standard_normal((n, 3)).astype(np.float32)
        pos = rng.standard_normal((n, 3)).astype(np.float32)
        send = np.arange(n, dtype=np.int64)
        recv = (send + 1) % n
        ei = np.stack([send, recv])
        g_y = rng.standard_normal(1).astype(np.float32)
        n_y = rng.standard_normal((n, 1)).astype(np.float32)
        y = np.concatenate([g_y, n_y.reshape(-1)])[:, None]
        y_loc = np.array([[0, 1, 1 + n]], dtype=np.int64)
        samples.append(
            {"x": x, "pos": pos, "edge_index": ei, "y": y, "y_loc": y_loc}
        )
        truth.append((x, pos, ei, g_y, n_y))
    return samples, truth


@pytest.fixture
def fake_bp(monkeypatch, tmp_path):
    _install_fake_adios2(monkeypatch)
    samples, truth = _make_truth(5)
    variables, attrs = _writer_schema(samples, "trainset")
    attrs["minmax_node_feature"] = np.arange(6, dtype=np.float32)
    # a real on-disk .bp directory (the CLI's dispatch checks existence);
    # the mocked adios2 serves its content from _FAKE_FILES
    bp = tmp_path / "dataset.bp"
    bp.mkdir()
    (bp / "md.idx").write_bytes(b"")
    path = str(bp)
    _FAKE_FILES[path] = (variables, attrs)
    yield path, truth
    _FAKE_FILES.pop(path, None)


def test_looks_like_adios(tmp_path):
    # nonexistent paths are never ADIOS (file-not-found must stay truthful)
    assert looks_like_adios("foo/gfm.bp") is False
    assert looks_like_adios(str(tmp_path)) is False
    bpfile = tmp_path / "gfm.bp"
    bpfile.write_bytes(b"")
    assert looks_like_adios(str(bpfile))
    bpdir = tmp_path / "x"
    bpdir.mkdir()
    (bpdir / "md.idx").write_bytes(b"")
    assert looks_like_adios(str(bpdir))


def test_reader_matches_fixture(fake_bp):
    path, truth = fake_bp
    reader = ReferenceAdiosReader(path, "trainset")
    assert len(reader) == 5
    assert reader.minmax_node_feature.shape == (2, 3)
    samples = reader.samples(
        head_types=["graph", "node"], head_names=["energy", "charge"]
    )
    for s, (x, pos, ei, g_y, n_y) in zip(samples, truth):
        np.testing.assert_allclose(s.x, x, rtol=1e-6)
        np.testing.assert_allclose(s.pos, pos, rtol=1e-6)
        np.testing.assert_array_equal(s.edge_index, ei)
        np.testing.assert_allclose(s.graph_targets["energy"], g_y, rtol=1e-6)
        np.testing.assert_allclose(s.node_targets["charge"], n_y, rtol=1e-6)


def test_unknown_label_lists_available(fake_bp):
    path, _ = fake_bp
    with pytest.raises(KeyError, match="trainset"):
        ReferenceAdiosReader(path, "valset")


def test_import_cli_dispatches_adios(fake_bp, tmp_path):
    from hydragnn_tpu.data.import_reference import main

    path, truth = fake_bp
    out = str(tmp_path / "imported.hgc")
    main(
        [
            path,
            "trainset",
            out,
            "--head-type=graph",
            "--head-type=node",
            "--head-name=energy",
            "--head-name=charge",
        ]
    )
    ds = ContainerDataset(out)
    assert len(ds) == 5
    for i, (x, pos, ei, g_y, n_y) in enumerate(truth):
        s = ds.get(i)
        np.testing.assert_allclose(s.x, x, rtol=1e-6)
        np.testing.assert_array_equal(s.edge_index, ei)
        np.testing.assert_allclose(s.graph_targets["energy"], g_y, rtol=1e-6)
        np.testing.assert_allclose(s.node_targets["charge"], n_y, rtol=1e-6)
    # the reference minmax metadata rides along as a container global
    assert ds.attrs.get("minmax_node_feature") is not None
    ds.close()


def test_export_script_two_step_roundtrip(fake_bp, tmp_path):
    """The standalone export script (reference-env side) emits the
    pickle layout the existing importer consumes: .bp -> pickles ->
    GraphSamples must equal the direct ADIOS read."""
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))
    try:
        import export_adios_to_pickle
    finally:
        sys.path.pop(0)

    path, truth = fake_bp
    out_dir = str(tmp_path / "export")
    n = export_adios_to_pickle.export(path, "trainset", out_dir)
    assert n == 5

    from hydragnn_tpu.data.import_reference import ReferencePickleReader

    reader = ReferencePickleReader(out_dir, "trainset")
    assert len(reader) == 5
    samples = reader.samples(
        head_types=["graph", "node"], head_names=["energy", "charge"]
    )
    for s, (x, pos, ei, g_y, n_y) in zip(samples, truth):
        np.testing.assert_allclose(s.x, x, rtol=1e-6)
        np.testing.assert_array_equal(s.edge_index, ei)
        np.testing.assert_allclose(s.graph_targets["energy"], g_y, rtol=1e-6)
        np.testing.assert_allclose(s.node_targets["charge"], n_y, rtol=1e-6)


def test_missing_adios2_error_points_at_export(tmp_path, monkeypatch):
    monkeypatch.setitem(sys.modules, "adios2", None)
    with pytest.raises(ImportError, match="export_adios_to_pickle"):
        ReferenceAdiosReader(str(tmp_path / "x.bp"), "trainset")
