"""Retrain-pilot tests (hydragnn_tpu/pilot): the crash-safe journal
(torn tails, SIGKILL resume classification), the drift -> fine-tune ->
canary -> reload state machine over injected tuner/reloader seams,
storm hysteresis (cooldown + single-retrain lock), escalation to the
terminal ``stuck`` state after K failed cycles, spool pinning across a
cycle, the probe/gauge contract serve_probe reads, and the fine-tune
child's split/scoring units.

Everything here runs against a fake server + fake clock so the state
machine is exercised exhaustively without training anything; the real
closed loop is driven end-to-end by ci.sh's pilot smoke stage."""

import json
import os
import sys

import numpy as np
import pytest

from hydragnn_tpu.obs.flight import FlightRecorder, read_flight_record
from hydragnn_tpu.obs.registry import MetricsRegistry
from hydragnn_tpu.obs.spool import RequestSpool, list_shards
from hydragnn_tpu.obs.triggers import TriggerVerdict
from hydragnn_tpu.pilot import (
    PILOT_STATES,
    PilotConfig,
    PilotJournal,
    RetrainPilot,
)
from hydragnn_tpu.pilot.journal import (
    JOURNAL_NAME,
    MID_CYCLE_STATES,
    RESTING_STATES,
)
from hydragnn_tpu.pilot.pilot import STATE_CODES, _sample_mae
from hydragnn_tpu.pilot.tune import _split


class FakeClock:
    def __init__(self, t=100.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


class FakeMetrics:
    def __init__(self):
        self.registry = MetricsRegistry(enabled=True)
        self.prefix = "serve"


class FakeServer:
    """The slice of ModelServer the pilot talks to, with bookkeeping."""

    def __init__(self, log_dir, flight=None, spool_root=None):
        self.log_dir = str(log_dir)
        self.flight = flight
        self.metrics = FakeMetrics()
        self._spool_root = spool_root
        self.pins = []  # currently held pin references
        self.unpin_calls = []
        self.drift_resets = 0
        self.pilot_incidents = []

    def pin_spool(self, shards):
        names = [os.path.basename(str(s)) for s in shards]
        self.pins.extend(names)
        return names

    def unpin_spool(self, shards):
        self.unpin_calls.append(list(shards))
        for s in shards:
            if s in self.pins:
                self.pins.remove(s)

    def spool_dir(self):
        return self._spool_root

    def reset_drift(self):
        self.drift_resets += 1

    def open_pilot_incident(self, verdict):
        self.pilot_incidents.append(verdict)
        return None


class FakeIncident:
    def __init__(self, root, report, inc_id="inc-1"):
        self.id = inc_id
        self.dir = str(root / inc_id)
        os.makedirs(self.dir, exist_ok=True)
        if report is not None:
            with open(os.path.join(self.dir, "drift_report.json"), "w") as f:
                json.dump(report, f)


def _verdict(kind="feature_drift"):
    return TriggerVerdict(
        "serve_feature_drift", kind, "serve.drift.feature_psi", 0.9, 0.25, 1.0
    )


def _incident(tmp_path, shards=("shard-000001",), inc_id="inc-1"):
    return FakeIncident(
        tmp_path, {"pinned_shards": list(shards)}, inc_id=inc_id
    )


def _pilot(
    tmp_path,
    *,
    server=None,
    tuner=None,
    reloader=None,
    clock=None,
    flight=None,
    canary=None,
    async_cycles=False,
    **cfg_kw,
):
    server = server or FakeServer(tmp_path / "logs", flight=flight)
    cfg_kw.setdefault("cooldown_s", 30.0)
    cfg_kw.setdefault("stuck_after", 3)
    p = RetrainPilot(
        server,
        "run",
        config=PilotConfig(**cfg_kw),
        tuner=tuner or (lambda c: {"status": "completed"}),
        reloader=reloader or (lambda c: {"ok": True}),
        clock=clock or FakeClock(),
        async_cycles=async_cycles,
    )
    # the real canary needs a served model; the state machine does not
    p._canary = canary or (lambda c: {"ok": True})
    return p, server


# ---------------------------------------------------------------------------
# journal: durability + restart classification
# ---------------------------------------------------------------------------


def test_journal_append_entries_roundtrip(tmp_path):
    j = PilotJournal(str(tmp_path / "j.jsonl"))
    j.append("idle", 0, 0, reason="fresh")
    j.append("drift_confirmed", 1, 0, rule="r")
    j.append("cooldown", 1, 1, reason="canary_regression")
    entries = j.entries()
    assert [e["state"] for e in entries] == [
        "idle", "drift_confirmed", "cooldown",
    ]
    assert entries[-1]["cycle"] == 1
    assert entries[-1]["failed_cycles"] == 1
    assert entries[-1]["detail"]["reason"] == "canary_regression"
    assert all("t" in e for e in entries)
    assert j.last() == entries[-1]


def test_journal_skips_torn_tail(tmp_path):
    """A SIGKILL mid-write leaves one torn line; readers skip it."""
    path = tmp_path / "j.jsonl"
    j = PilotJournal(str(path))
    j.append("idle", 0, 0)
    j.append("fine_tuning", 1, 0)
    with open(path, "a") as f:
        f.write('{"t": 1.0, "state": "can')  # torn mid-record
    assert [e["state"] for e in j.entries()] == ["idle", "fine_tuning"]
    assert j.last()["state"] == "fine_tuning"


def test_journal_recover_classification(tmp_path):
    j = PilotJournal(str(tmp_path / "j.jsonl"))
    assert j.recover() == {"status": "fresh"}
    for state in RESTING_STATES:
        j.append(state, 3, 1)
        rec = j.recover()
        assert rec["status"] == "clean"
        assert rec["state"] == state
        assert (rec["cycle"], rec["failed_cycles"]) == (3, 1)
    for state in MID_CYCLE_STATES:
        j.append(state, 4, 1)
        assert j.recover()["status"] == "crashed_mid_cycle"


# ---------------------------------------------------------------------------
# restart recovery: the SIGKILL-resume contract
# ---------------------------------------------------------------------------


def test_fresh_pilot_starts_idle(tmp_path):
    p, _ = _pilot(tmp_path)
    assert p.state == "idle"
    assert (p.cycle, p.failed_cycles) == (0, 0)
    # the idle transition was journaled (the NEXT restart is "clean")
    assert p.journal.last()["state"] == "idle"


def test_sigkill_mid_cycle_resumes_into_cooldown(tmp_path):
    """The crashed-pilot signature: a mid-cycle tail (plus the torn
    partial line the kill left) recovers into cooldown with the crashed
    cycle counted against the failure budget — never into resuming the
    half-done retrain."""
    jpath = tmp_path / "logs" / "run" / JOURNAL_NAME
    j = PilotJournal(str(jpath))
    j.append("drift_confirmed", 2, 0)
    j.append("fine_tuning", 2, 0, candidate="run-pilot-c2")
    with open(jpath, "a") as f:
        f.write('{"t": 9.9, "state": "fi')  # killed mid-append
    p, _ = _pilot(tmp_path)
    assert p.state == "cooldown"
    assert p.cycle == 2
    assert p.failed_cycles == 1
    assert p.last_cycle_ok is False
    tail = p.journal.last()
    assert tail["detail"]["reason"] == "recovered_after_crash"
    assert tail["detail"]["crashed_in"] == "fine_tuning"


def test_crash_recovery_escalates_when_budget_spent(tmp_path):
    j = PilotJournal(str(tmp_path / "logs" / "run" / JOURNAL_NAME))
    j.append("canary", 5, 2)  # two failures already burned
    p, server = _pilot(tmp_path, stuck_after=3)
    assert p.state == "stuck"
    assert p.failed_cycles == 3
    assert [v.kind for v in server.pilot_incidents] == ["pilot_stuck"]
    assert server.pilot_incidents[0].observed == 3.0


def test_recovered_stuck_stays_stuck(tmp_path):
    j = PilotJournal(str(tmp_path / "logs" / "run" / JOURNAL_NAME))
    j.append("stuck", 7, 3)
    p, _ = _pilot(tmp_path)
    assert p.state == "stuck"
    # stuck is terminal: a new incident is suppressed, not flown
    assert not p.on_drift_incident(_incident(tmp_path), _verdict())
    assert p.suppressed == 1


def test_recovered_cooldown_restamps_clock_then_expires(tmp_path):
    j = PilotJournal(str(tmp_path / "logs" / "run" / JOURNAL_NAME))
    j.append("cooldown", 1, 1, reason="canary_regression")
    clk = FakeClock()
    p, _ = _pilot(tmp_path, clock=clk, cooldown_s=30.0)
    assert p.poll() == "cooldown"  # wall time restarts at recovery
    clk.advance(29.0)
    assert p.poll() == "cooldown"
    clk.advance(1.1)
    assert p.poll() == "idle"
    assert p.failed_cycles == 1  # the counter survives the rest


# ---------------------------------------------------------------------------
# one cycle through the seams
# ---------------------------------------------------------------------------


def test_successful_cycle_end_to_end(tmp_path):
    tuned, reloaded = [], []
    flight = FlightRecorder(str(tmp_path / "flight.jsonl"))
    server = FakeServer(tmp_path / "logs", flight=flight)
    p, server = _pilot(
        tmp_path,
        server=server,
        tuner=lambda c: tuned.append(c) or {"status": "completed"},
        reloader=lambda c: reloaded.append(c),
    )
    started = p.on_drift_incident(_incident(tmp_path), _verdict())
    assert started
    assert tuned == ["run-pilot-c1"]  # distinct candidate run name
    assert reloaded == ["run-pilot-c1"]
    assert server.drift_resets == 1  # fresh weights, fresh sketches
    assert p.state == "cooldown"
    assert p.last_cycle_ok is True
    assert p.failed_cycles == 0
    # the journal narrates every stage, in order
    assert [e["state"] for e in p.journal.entries()] == [
        "idle", "drift_confirmed", "fine_tuning", "canary",
        "reloading", "cooldown",
    ]
    assert p.journal.last()["detail"]["reason"] == "reloaded"
    # ...and so does the flight record
    states = [
        e["state"] for e in read_flight_record(str(tmp_path / "flight.jsonl"))
        if e["kind"] == "pilot"
    ]
    assert states[-1] == "cooldown" and "drift_confirmed" in states


def test_storm_hysteresis_suppresses_incidents_in_cooldown(tmp_path):
    clk = FakeClock()
    p, server = _pilot(tmp_path, clock=clk, cooldown_s=30.0)
    assert p.on_drift_incident(_incident(tmp_path), _verdict())
    assert p.state == "cooldown"
    # a storm of repeat incidents inside the cooldown window: counted,
    # never acted on
    for i in range(3):
        assert not p.on_drift_incident(
            _incident(tmp_path, inc_id=f"storm-{i}"), _verdict()
        )
    assert p.suppressed == 3
    assert p.cycle == 1
    reg = server.metrics.registry
    assert reg.gauge("serve.pilot.suppressed").value == 3.0
    # cooldown elapses -> the next incident flies a new cycle
    clk.advance(31.0)
    assert p.on_drift_incident(_incident(tmp_path, inc_id="later"), _verdict())
    assert p.cycle == 2


def test_incident_during_running_cycle_is_suppressed(tmp_path):
    """The single-retrain lock: an incident arriving while the tuner is
    mid-flight must not start a second cycle."""
    cell, inner = {}, []

    def tuner(candidate):
        inner.append(
            cell["p"].on_drift_incident(
                _incident(tmp_path, inc_id="inner"), _verdict()
            )
        )
        return {"status": "completed"}

    p, _ = _pilot(tmp_path, tuner=tuner)
    cell["p"] = p
    assert p.on_drift_incident(_incident(tmp_path), _verdict())
    assert inner == [False]
    assert p.suppressed == 1
    assert p.cycle == 1


def test_tuner_gave_up_lands_cooldown(tmp_path):
    p, server = _pilot(
        tmp_path,
        tuner=lambda c: {"status": "gave_up", "attempts": 3, "cause": "crash"},
    )
    p.on_drift_incident(_incident(tmp_path), _verdict())
    assert p.state == "cooldown"
    assert p.failed_cycles == 1
    assert p.last_cycle_ok is False
    tail = p.journal.last()["detail"]
    assert tail["reason"] == "fine_tune_gave_up"
    assert tail["cause"] == "crash"
    assert server.drift_resets == 0  # old weights, old sketches


def test_tuner_exception_lands_cooldown(tmp_path):
    def tuner(c):
        raise RuntimeError("supervisor exploded")

    p, _ = _pilot(tmp_path, tuner=tuner)
    p.on_drift_incident(_incident(tmp_path), _verdict())
    assert p.state == "cooldown"
    assert p.journal.last()["detail"]["reason"] == "fine_tune_error"


def test_canary_regression_rejects_without_reload(tmp_path):
    reloaded = []
    regress = {
        "ok": False,
        "reference": {
            "baseline_mae": 0.1, "candidate_mae": 9.0, "passed": False,
        },
        "window": None,
    }
    p, server = _pilot(
        tmp_path, reloader=lambda c: reloaded.append(c),
        canary=lambda c: dict(regress),
    )
    p.on_drift_incident(_incident(tmp_path), _verdict())
    assert reloaded == []  # never got near the weights
    assert server.drift_resets == 0
    assert p.state == "cooldown"
    tail = p.journal.last()["detail"]
    assert tail["reason"] == "canary_regression"
    assert tail["reference"]["passed"] is False


def test_reload_failure_keeps_old_weights(tmp_path):
    from hydragnn_tpu.serve.server import ReloadFailed

    def reloader(c):
        raise ReloadFailed("canary rejected torn checkpoint")

    p, server = _pilot(tmp_path, reloader=reloader)
    p.on_drift_incident(_incident(tmp_path), _verdict())
    assert p.state == "cooldown"
    assert p.journal.last()["detail"]["reason"] == "reload_failed"
    assert server.drift_resets == 0  # the old model is still the model


def test_escalates_stuck_after_k_failed_cycles(tmp_path):
    clk = FakeClock()
    p, server = _pilot(
        tmp_path,
        clock=clk,
        stuck_after=2,
        cooldown_s=10.0,
        tuner=lambda c: {"status": "gave_up", "cause": "crash"},
    )
    assert p.on_drift_incident(_incident(tmp_path, inc_id="a"), _verdict())
    assert p.state == "cooldown"
    clk.advance(11.0)
    assert p.on_drift_incident(_incident(tmp_path, inc_id="b"), _verdict())
    assert p.state == "stuck"
    assert p.failed_cycles == 2
    # the escalation pages: one pilot_stuck incident verdict
    assert [v.kind for v in server.pilot_incidents] == ["pilot_stuck"]
    v = server.pilot_incidents[0]
    assert v.observed == 2.0 and v.threshold == 2.0
    # terminal: no amount of waiting re-arms it
    clk.advance(1000.0)
    assert not p.on_drift_incident(_incident(tmp_path, inc_id="c"), _verdict())
    assert p.poll() == "stuck"


def test_async_cycle_runs_on_worker_thread(tmp_path):
    import threading

    seen = []
    p, _ = _pilot(
        tmp_path,
        async_cycles=True,
        tuner=lambda c: seen.append(threading.current_thread().name)
        or {"status": "completed"},
    )
    assert p.on_drift_incident(_incident(tmp_path), _verdict())
    p.join(timeout=30.0)
    assert p.state == "cooldown"
    assert seen == ["pilot-cycle-1"]  # never the notifying thread


# ---------------------------------------------------------------------------
# spool pinning across a cycle
# ---------------------------------------------------------------------------


def test_pins_held_through_cycle_released_after(tmp_path):
    held_during_tune = []

    def tuner(c):
        held_during_tune.append(list(cell["server"].pins))
        return {"status": "completed"}

    cell = {}
    p, server = _pilot(tmp_path, tuner=tuner)
    cell["server"] = server
    p.on_drift_incident(
        _incident(tmp_path, shards=("shard-000003", "shard-000004")),
        _verdict(),
    )
    # the fine-tune ran with its input set pinned against eviction...
    assert held_during_tune == [["shard-000003", "shard-000004"]]
    # ...and the pins are released once the cycle lands (success path)
    assert server.pins == []
    assert server.unpin_calls == [["shard-000003", "shard-000004"]]


def test_pins_released_on_failed_cycle_too(tmp_path):
    def tuner(c):
        raise RuntimeError("boom")

    p, server = _pilot(tmp_path, tuner=tuner)
    p.on_drift_incident(_incident(tmp_path, shards=("shard-000009",)), _verdict())
    assert p.state == "cooldown"
    assert server.pins == []


def test_incident_shards_reads_drift_report(tmp_path):
    inc = FakeIncident(
        tmp_path, {"pinned_shards": ["shard-000002"]}, inc_id="pinned"
    )
    assert RetrainPilot._incident_shards(inc) == ["shard-000002"]
    inc = FakeIncident(
        tmp_path,
        {"spool_window": {"shards": ["shard-000005", "shard-000006"]}},
        inc_id="window",
    )
    assert RetrainPilot._incident_shards(inc) == [
        "shard-000005", "shard-000006",
    ]
    inc = FakeIncident(tmp_path, None, inc_id="bare")  # no report at all
    assert RetrainPilot._incident_shards(inc) == []


def _toy_samples(n, nodes=64, seed=0):
    from hydragnn_tpu.data.dataset import GraphSample

    rng = np.random.default_rng(seed)
    out = []
    for _ in range(n):
        ei = np.stack(
            [np.arange(nodes), (np.arange(nodes) + 1) % nodes]
        ).astype(np.int32)
        out.append(
            GraphSample(
                x=rng.normal(size=(nodes, 2)).astype(np.float32),
                pos=rng.normal(size=(nodes, 3)).astype(np.float32),
                edge_index=ei,
                graph_targets={"energy": np.float32(rng.normal())},
                node_targets={
                    "forces": rng.normal(size=(nodes, 1)).astype(np.float32)
                },
            )
        )
    return out


def _offer_all(spool, samples, start=0):
    for i, s in enumerate(samples, start=start):
        ei = np.asarray(s.edge_index)
        g = {
            "x": np.asarray(s.x),
            "pos": np.asarray(s.pos),
            "senders": ei[0],
            "receivers": ei[1],
        }
        result = {
            "energy": np.asarray([0.5], np.float32),
            "forces": np.zeros((ei.shape[1], 1), np.float32),
        }
        spool.offer(g, result, seq=i)


def test_spool_pin_blocks_eviction_until_unpin(tmp_path):
    head_kinds = {"energy": "graph", "forces": "node"}
    samples = _toy_samples(48)
    spool = RequestSpool(
        str(tmp_path / "spool"),
        sample_every=1,
        max_mb=0.02,  # ~2 shards' worth: every rotation evicts
        shard_mb=0.01,
        head_kinds=head_kinds,
    )
    _offer_all(spool, samples[:8])
    first = spool.flush_pending()
    assert first is not None
    assert spool.pin([first]) == [first]
    _offer_all(spool, samples[8:40], start=8)
    spool.flush_pending()
    names = [os.path.basename(s) for s in list_shards(str(tmp_path / "spool"))]
    assert first in names, "pinned shard was evicted under the pin"
    assert spool.pinned() == {first: 1}
    # release the pin: the next eviction pass reclaims it (oldest = LRU)
    spool.unpin([first])
    _offer_all(spool, samples[40:], start=40)
    spool.flush_pending()
    names = [os.path.basename(s) for s in list_shards(str(tmp_path / "spool"))]
    assert first not in names


def test_spool_pin_refcounts_and_skips_missing(tmp_path):
    root = tmp_path / "spool"
    os.makedirs(root / "shard-000001")
    (root / "shard-000001" / "blob").write_text("x")
    spool = RequestSpool(str(root), sample_every=1)
    # a vanished shard is skipped, not an error — the caller learns
    # what survives from the return value
    assert spool.pin(["shard-000001", "shard-999999"]) == ["shard-000001"]
    assert spool.pin([str(root / "shard-000001")]) == ["shard-000001"]  # path ok
    assert spool.pinned() == {"shard-000001": 2}
    spool.unpin(["shard-000001"])
    assert spool.pinned() == {"shard-000001": 1}
    spool.unpin(["shard-000001"])
    spool.unpin(["shard-000001"])  # over-unpin is a no-op
    assert spool.pinned() == {}


# ---------------------------------------------------------------------------
# gauges / probe contract
# ---------------------------------------------------------------------------


def test_gauges_and_status_track_the_machine(tmp_path):
    p, server = _pilot(
        tmp_path, tuner=lambda c: {"status": "gave_up", "cause": "hung"}
    )
    reg = server.metrics.registry
    assert reg.gauge("serve.pilot.state").value == STATE_CODES["idle"]
    assert reg.gauge("serve.pilot.last_cycle_ok").value == -1.0  # no cycle yet
    p.on_drift_incident(_incident(tmp_path), _verdict())
    assert reg.gauge("serve.pilot.state").value == STATE_CODES["cooldown"]
    assert reg.gauge("serve.pilot.last_cycle_ok").value == 0.0
    assert reg.gauge("serve.pilot.cycles").value == 1.0
    assert reg.gauge("serve.pilot.failed_cycles").value == 1.0
    st = p.status()
    assert st == {
        "state": "cooldown",
        "cycle": 1,
        "failed_cycles": 1,
        "suppressed": 0,
        "last_cycle_ok": False,
        "pinned_shards": [],
    }


def _probe_pilot():
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))
    try:
        import serve_probe

        return serve_probe
    finally:
        sys.path.pop(0)


def test_serve_probe_state_table_matches_pilot():
    sp = _probe_pilot()
    assert tuple(sp._PILOT_STATES) == PILOT_STATES
    assert sp._PILOT_STUCK == STATE_CODES["stuck"]


def test_serve_probe_pilot_exit_codes(tmp_path):
    sp = _probe_pilot()
    prom = tmp_path / "serve.prom"

    def write(state, last_ok):
        prom.write_text(
            f"hydragnn_serve_pilot_state {state}\n"
            f"hydragnn_serve_pilot_last_cycle_ok {last_ok}\n"
        )

    write(STATE_CODES["idle"], -1)
    rc, msg = sp.probe_pilot(str(prom))
    assert rc == 0 and "idle" in msg
    write(STATE_CODES["cooldown"], 1)
    assert sp.probe_pilot(str(prom))[0] == 0
    write(STATE_CODES["cooldown"], 0)  # last cycle failed: look at it
    rc, msg = sp.probe_pilot(str(prom))
    assert rc == 1 and "failed" in msg
    write(STATE_CODES["stuck"], 0)
    rc, msg = sp.probe_pilot(str(prom))
    assert rc == 1 and "STUCK" in msg
    prom.write_text("hydragnn_serve_ready 1\n")  # server yes, pilot no
    assert sp.probe_pilot(str(prom))[0] == 2
    assert sp.probe_pilot(str(tmp_path / "missing.prom"))[0] == 2
    write(STATE_CODES["idle"], -1)
    old = os.stat(prom).st_mtime - 3600
    os.utime(prom, (old, old))
    assert sp.probe_pilot(str(prom), max_age_s=60.0)[0] == 2  # stale


def test_serve_probe_pilot_cli_requires_prom(tmp_path, capsys):
    sp = _probe_pilot()
    prom = tmp_path / "serve.prom"
    prom.write_text(
        f"hydragnn_serve_pilot_state {STATE_CODES['idle']}\n"
        "hydragnn_serve_pilot_last_cycle_ok -1\n"
    )
    assert sp.main(["--prom", str(prom), "--pilot"]) == 0
    assert sp.main(["--fleet", str(tmp_path), "--pilot"]) == 2


# ---------------------------------------------------------------------------
# the hard wall-clock belt around the fine-tune child
# ---------------------------------------------------------------------------


def test_wall_clock_runner_kills_wedged_child():
    import time

    from hydragnn_tpu.resilience.supervisor import EXIT_HUNG, wall_clock_runner

    runner = wall_clock_runner(0.3, grace_s=5.0)
    t0 = time.monotonic()
    rc = runner(
        [sys.executable, "-c", "import time; time.sleep(60)"],
        dict(os.environ),
    )
    assert rc == EXIT_HUNG
    assert time.monotonic() - t0 < 30.0  # killed, not waited out
    # a child that exits on its own reports its OWN code
    assert (
        runner([sys.executable, "-c", "raise SystemExit(7)"], dict(os.environ))
        == 7
    )


def test_supervisor_classifies_wall_clock_kill_as_hung():
    from hydragnn_tpu.resilience.supervisor import (
        Supervisor,
        SupervisorPolicy,
        wall_clock_runner,
    )

    sup = Supervisor(
        [sys.executable, "-c", "import time; time.sleep(60)"],
        policy=SupervisorPolicy(max_restarts=1, backoff_base_s=0.01),
        env=dict(os.environ),
        runner=wall_clock_runner(0.3, grace_s=5.0),
    )
    out = sup.run()
    assert out["status"] == "gave_up"
    assert out["cause"] == "hung"
    assert out["attempts"] == 2  # retried once with backoff, then gave up


# ---------------------------------------------------------------------------
# fine-tune child units
# ---------------------------------------------------------------------------


def test_split_deterministic_and_never_empty():
    train, val, test = _split(list(range(24)))
    assert len(train) == 20 and len(val) == 2 and len(test) == 2
    assert set(train) | set(val) | set(test) == set(range(24))
    # tiny windows backfill from train rather than starving a loader
    train, val, test = _split([0, 1, 2])
    assert len(train) == 1 and len(val) == 1 and len(test) == 1
    with pytest.raises(ValueError):
        _split([0, 1])


def test_sample_mae_matches_numpy():
    from hydragnn_tpu.data.dataset import GraphSample

    n = 5
    sample = GraphSample(
        x=np.zeros((n, 2), np.float32),
        pos=np.zeros((n, 3), np.float32),
        edge_index=np.zeros((2, n), np.int32),
        graph_targets={"energy": np.asarray([1.0], np.float32)},
        node_targets={"forces": np.zeros((n, 1), np.float32)},
    )
    result = {
        "energy": np.asarray([1.5]),
        "forces": np.full((n, 1), 0.25),
        "mystery": np.asarray([9.9]),  # no matching target: skipped
    }
    want = np.mean([0.5, 0.25])
    assert _sample_mae(result, sample) == pytest.approx(want)
    # no overlapping heads -> 0.0, not a crash
    assert _sample_mae({"mystery": np.asarray([1.0])}, sample) == 0.0


def test_pilot_knobs_are_consumed_and_documented():
    """Every HYDRAGNN_PILOT_* / HYDRAGNN_INJECT_PILOT_* knob is declared
    with a consumer (the graftlint HG006 contract) and survives a
    config round-trip through PilotConfig."""
    from hydragnn_tpu.utils.knobs import KNOBS

    names = set(KNOBS)
    for suffix in (
        "CANARY_SAMPLES", "CANARY_TOL", "COOLDOWN_S", "MAX_WALL_S",
        "STUCK_AFTER", "TUNE_ATTEMPTS", "TUNE_BACKOFF_S", "TUNE_EPOCHS",
    ):
        assert f"HYDRAGNN_PILOT_{suffix}" in names
    for suffix in ("TRAIN_CRASH", "CANARY_REGRESS", "TORN_RELOAD", "HUNG_TUNE"):
        assert f"HYDRAGNN_INJECT_PILOT_{suffix}" in names
    cfg = PilotConfig()
    assert cfg.cooldown_s == 60.0 and cfg.stuck_after == 3
