"""Native XYZ/CFG parser tests with generated files.

The reference covers these readers implicitly through ase; here the
parsers are native, so the tests generate files in both formats and check
the exact GraphSample packing the reference produces (x column orders,
sidecar column selection, cell recovery)."""

import os

import numpy as np

from hydragnn_tpu.data.formats import (
    read_cfg_file,
    read_cfg_sample,
    read_xyz_file,
    read_xyz_sample,
)


def _write_xyz(path, with_lattice=True):
    lattice = 'Lattice="5.0 0.0 0.0 0.0 6.0 0.0 0.0 0.0 7.0" ' if with_lattice else ""
    content = (
        "3\n"
        f"{lattice}Properties=species:S:1:pos:R:3\n"
        "Fe 0.0 0.0 0.0\n"
        "Pt 1.5 1.5 1.5\n"
        "H 2.0 2.5 3.0\n"
    )
    with open(path, "w") as f:
        f.write(content)
    with open(os.path.splitext(path)[0] + "_energy.txt", "w") as f:
        f.write("-123.45 0.5 7.7\n")


def _write_cfg(path):
    content = """Number of particles = 3
A = 1.0 Angstrom (basic length-scale)
H0(1,1) = 4.0 A
H0(1,2) = 0.0 A
H0(1,3) = 0.0 A
H0(2,1) = 0.0 A
H0(2,2) = 4.0 A
H0(2,3) = 0.0 A
H0(3,1) = 0.0 A
H0(3,2) = 0.0 A
H0(3,3) = 4.0 A
.NO_VELOCITY.
entry_count = 7
auxiliary[0] = c_peratom
auxiliary[1] = fx
auxiliary[2] = fy
auxiliary[3] = fz
55.845
Fe
0.0 0.0 0.0 1.1 0.1 0.2 0.3
0.5 0.5 0.5 2.2 0.4 0.5 0.6
195.084
Pt
0.25 0.25 0.75 3.3 0.7 0.8 0.9
"""
    with open(path, "w") as f:
        f.write(content)
    with open(os.path.splitext(path)[0] + ".bulk", "w") as f:
        f.write("42.5 99.0\n")


def pytest_xyz_parse(tmp_path):
    p = str(tmp_path / "s1.xyz")
    _write_xyz(p)
    zs, pos, cell = read_xyz_file(p)
    np.testing.assert_array_equal(zs, [26, 78, 1])
    np.testing.assert_allclose(pos[1], [1.5, 1.5, 1.5])
    np.testing.assert_allclose(cell, np.diag([5.0, 6.0, 7.0]))


def pytest_xyz_sample_with_sidecar(tmp_path):
    p = str(tmp_path / "s2.xyz")
    _write_xyz(p)
    # graph feature: 1 feature of dim 2 starting at column 1 -> [0.5, 7.7]
    s = read_xyz_sample(p, [2], [1])
    np.testing.assert_allclose(s.graph_y, [0.5, 7.7])
    np.testing.assert_array_equal(s.x[:, 0], [26, 78, 1])
    np.testing.assert_allclose(s.meta["cell"], np.diag([5.0, 6.0, 7.0]))


def pytest_xyz_without_lattice(tmp_path):
    p = str(tmp_path / "s3.xyz")
    _write_xyz(p, with_lattice=False)
    s = read_xyz_sample(p, [1], [0])
    assert "cell" not in s.meta
    np.testing.assert_allclose(s.graph_y, [-123.45])


def pytest_cfg_parse(tmp_path):
    p = str(tmp_path / "c1.cfg")
    _write_cfg(p)
    parsed = read_cfg_file(p)
    np.testing.assert_array_equal(parsed["numbers"], [26, 26, 78])
    np.testing.assert_allclose(parsed["masses"], [55.845, 55.845, 195.084])
    np.testing.assert_allclose(parsed["cell"], np.eye(3) * 4.0)
    # reduced (0.5,0.5,0.5) @ 4A cell -> (2,2,2)
    np.testing.assert_allclose(parsed["pos"][1], [2.0, 2.0, 2.0])
    np.testing.assert_allclose(parsed["c_peratom"], [1.1, 2.2, 3.3])
    np.testing.assert_allclose(parsed["fz"], [0.3, 0.6, 0.9])


def pytest_cfg_sample_packing(tmp_path):
    p = str(tmp_path / "c2.cfg")
    _write_cfg(p)
    s = read_cfg_sample(p, [1], [0])
    # reference packing: [Z, mass, c_peratom, fx, fy, fz]
    np.testing.assert_allclose(
        s.x[2], [78, 195.084, 3.3, 0.7, 0.8, 0.9], rtol=1e-6
    )
    np.testing.assert_allclose(s.graph_y, [42.5])
    np.testing.assert_allclose(s.meta["cell"], np.eye(3) * 4.0)
