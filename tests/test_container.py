"""HGC container tests: write/read round-trip in all three modes, native
gather vs numpy slicing, attribute storage, loader integration.

Mirrors the reference's ADIOS round-trip usage (reference:
examples/ising_model/train_ising.py:232-279 writes with AdiosWriter and
reads back with AdiosDataset in preload/shmem modes)."""

import os

import numpy as np
import pytest

from hydragnn_tpu.data.container import ContainerDataset, ContainerWriter
from hydragnn_tpu.data.ingest import prepare_dataset
from hydragnn_tpu.data.synthetic import deterministic_graph_data
from hydragnn_tpu.native import HAVE_NATIVE, _load

from test_data_pipeline import base_config


@pytest.fixture(scope="module")
def built_samples():
    """Samples with edges + targets (the state the scalable path writes)."""
    cfg = base_config(multihead=True)
    samples = deterministic_graph_data(number_configurations=30, seed=7)
    train, val, test, mm_g, mm_n = prepare_dataset(samples, cfg)
    return train, mm_g, mm_n


def _assert_sample_equal(a, b):
    np.testing.assert_array_equal(a.x, b.x)
    np.testing.assert_array_equal(a.pos, b.pos)
    np.testing.assert_array_equal(a.edge_index, b.edge_index)
    np.testing.assert_allclose(a.edge_attr, b.edge_attr, rtol=1e-6)
    assert sorted(a.graph_targets) == sorted(b.graph_targets)
    for k in a.graph_targets:
        np.testing.assert_allclose(a.graph_targets[k], b.graph_targets[k], rtol=1e-6)
    for k in a.node_targets:
        np.testing.assert_allclose(a.node_targets[k], b.node_targets[k], rtol=1e-6)


@pytest.mark.parametrize("mode", ["mmap", "preload", "shm"])
def pytest_container_roundtrip(built_samples, tmp_path, mode):
    train, mm_g, mm_n = built_samples
    path = str(tmp_path / "c.hgc")
    w = ContainerWriter(path)
    w.add(train)
    w.add_global("minmax_graph_feature", mm_g)
    w.add_global("minmax_node_feature", mm_n)
    w.save()

    shm_dir = str(tmp_path / "shm") if mode == "shm" else None
    ds = ContainerDataset(path, mode=mode, shm_dir=shm_dir)
    assert len(ds) == len(train)
    for i in (0, len(train) // 2, len(train) - 1):
        _assert_sample_equal(train[i], ds.get(i))
    g, n = ds.minmax()
    np.testing.assert_allclose(g, mm_g)
    np.testing.assert_allclose(n, mm_n)
    ds.close()


def pytest_meta_roundtrip(tmp_path):
    """Sample meta (PBC cell etc.) must survive the container round-trip —
    ingest's PBC edge building requires meta['cell']."""
    from hydragnn_tpu.data.dataset import GraphSample

    s = GraphSample(
        x=np.ones((3, 2), dtype=np.float32),
        pos=np.zeros((3, 3), dtype=np.float32),
        edge_index=np.array([[0, 1], [1, 0]], dtype=np.int32),
        meta={"cell": np.eye(3) * 5.0, "composition": "FePt"},
    )
    s2 = GraphSample(
        x=np.ones((2, 2), dtype=np.float32),
        pos=np.zeros((2, 3), dtype=np.float32),
        edge_index=np.zeros((2, 0), dtype=np.int32),
        meta={},
    )
    path = str(tmp_path / "m.hgc")
    w = ContainerWriter(path)
    w.add([s, s2])
    w.save()
    ds = ContainerDataset(path)
    got = ds.get(0)
    np.testing.assert_allclose(got.meta["cell"], np.eye(3) * 5.0)
    assert got.meta["composition"] == "FePt"
    assert ds.get(1).meta == {}
    # zero-edge sample: the empty field file must still read cleanly
    assert ds.get(1).edge_index.shape[1] == 0
    ds.close()


def pytest_native_gather_matches_slicing(built_samples, tmp_path):
    train, _, _ = built_samples
    path = str(tmp_path / "g.hgc")
    w = ContainerWriter(path)
    w.add(train)
    w.save()

    ds = ContainerDataset(path, mode="mmap")
    idx = [5, 0, 17, 3, 3]
    packed, cnt = ds.fetch_rows("x", idx)
    expect = np.concatenate([train[i].x for i in idx], axis=0)
    np.testing.assert_array_equal(packed, expect)
    np.testing.assert_array_equal(cnt, [train[i].x.shape[0] for i in idx])
    ds.close()


def pytest_native_library_builds():
    """The C++ core must actually compile in this environment — the numpy
    fallback is for degraded environments only."""
    _load()
    from hydragnn_tpu import native

    assert native.HAVE_NATIVE, "libhgc.so failed to build; check g++"


def pytest_container_feeds_training(built_samples, tmp_path):
    """Container -> loader -> one jitted train step (the scalable data
    path end-to-end)."""
    from hydragnn_tpu.data.loader import GraphLoader
    from hydragnn_tpu.models.create import create_model_config
    from hydragnn_tpu.train import create_train_state, make_train_step, select_optimizer
    from hydragnn_tpu.utils.config import update_config

    train, _, _ = built_samples
    path = str(tmp_path / "t.hgc")
    w = ContainerWriter(path)
    w.add(train)
    w.save()

    ds = ContainerDataset(path, mode="preload")
    samples = ds.samples()
    cfg = base_config(multihead=True)
    cfg = update_config(cfg, samples, samples, samples)
    loader = GraphLoader(samples, 8)
    batch = next(iter(loader))
    model, variables = create_model_config(cfg["NeuralNetwork"], batch)
    tx = select_optimizer({"Optimizer": {"type": "AdamW", "learning_rate": 1e-3}})
    state = create_train_state(variables, tx)
    _, loss, _ = make_train_step(model, tx)(state, batch)
    assert np.isfinite(float(loss))
    ds.close()


def pytest_fetch_samples_bulk_matches_get(built_samples, tmp_path):
    """fetch_samples materializes an index list in one bulk read per
    field (reference: AdiosDataset bulk preflight loader,
    adiosdataset.py:389-437) — must equal per-sample get() exactly, in
    every mode, including out-of-order and repeated indices."""
    samples, _, _ = built_samples
    path = str(tmp_path / "bulk.hgc")
    w = ContainerWriter(path)
    w.add(samples[:12])
    w.save()

    for mode in ("mmap", "preload"):
        ds = ContainerDataset(path, mode=mode)
        idx = [7, 0, 3, 7, 11]
        bulk = ds.fetch_samples(idx)
        assert len(bulk) == len(idx)
        for want_i, got in zip(idx, bulk):
            ref = ds.get(want_i)
            np.testing.assert_array_equal(got.x, ref.x)
            np.testing.assert_array_equal(got.edge_index, ref.edge_index)
            for k in ref.node_targets:
                np.testing.assert_array_equal(got.node_targets[k], ref.node_targets[k])
            for k in ref.graph_targets:
                np.testing.assert_array_equal(got.graph_targets[k], ref.graph_targets[k])
        with pytest.raises(IndexError):
            ds.fetch_samples([0, 99])
        ds.close()
