"""Fused conv kernel (ops/fused_conv.py): forward + VJP must match the
XLA reference composition in Pallas interpret mode on CPU — the tier-1
pin for the TPU kernel path — across masked/padded segments, both
edge-feature modes (receiver-table only vs receiver-table + per-edge
edge term), bf16/f32, and the model-level wiring."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from hydragnn_tpu.ops.fused_conv import _fused_ref, fused_conv


@pytest.fixture
def case():
    rng = np.random.default_rng(11)
    e, n, h = 900, 120, 128
    # sorted receivers with empty segments at the tail (padding nodes)
    recv = np.sort(rng.integers(0, n - 15, e)).astype(np.int32)
    send = rng.integers(0, n, e).astype(np.int32)
    mask = rng.random(e) > 0.2
    x = rng.normal(size=(n, h)).astype(np.float32)
    return (
        jnp.asarray(x),
        jnp.asarray(send),
        jnp.asarray(recv),
        jnp.asarray(mask),
        n,
    )


def _np_identity_reference(x, send, recv, mask, n):
    out = np.zeros((n, x.shape[1]), np.float64)
    xs = np.asarray(x, np.float64)
    for e in range(len(send)):
        if mask[e]:
            out[recv[e]] += xs[send[e]]
    return out


def pytest_identity_matches_numpy(case, monkeypatch):
    monkeypatch.setenv("HYDRAGNN_PALLAS", "interpret")
    x, send, recv, mask, n = case
    out = fused_conv(x, send, recv, mask, n)
    ref = _np_identity_reference(
        np.asarray(x), np.asarray(send), np.asarray(recv), np.asarray(mask), n
    )
    np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def pytest_identity_and_scale_match_ref(case, monkeypatch, dtype):
    monkeypatch.setenv("HYDRAGNN_PALLAS", "interpret")
    x, send, recv, mask, n = case
    x = x.astype(dtype)
    rng = np.random.default_rng(1)
    scale = jnp.asarray(
        rng.normal(size=(send.shape[0], x.shape[1])).astype(np.float32)
    ).astype(dtype)
    out = fused_conv(x, send, recv, mask, n, scale=scale)
    ref = _fused_ref((0, ()), n, x, send, recv, mask, (), scale)
    tol = 1e-4 if dtype == jnp.float32 else 5e-2
    scale_ref = float(jnp.abs(ref).max()) or 1.0
    assert float(jnp.abs(out - ref).max()) / scale_ref < tol


@pytest.mark.parametrize("with_eterm", [False, True])
def pytest_glu_both_edge_feature_modes(case, monkeypatch, with_eterm):
    """The CGCNN gate shape: two branches, sigmoid*softplus, receiver
    tables, with and without the additive per-edge term (edge features)."""
    monkeypatch.setenv("HYDRAGNN_PALLAS", "interpret")
    x, send, recv, mask, n = case
    h = x.shape[1]
    rng = np.random.default_rng(2)

    def arr(*shape, s=0.1):
        return jnp.asarray((rng.normal(size=shape) * s).astype(np.float32))

    e = send.shape[0]
    et1 = arr(e, h) if with_eterm else None
    et2 = arr(e, h) if with_eterm else None
    branches = (
        (arr(h, h), None, arr(n, h), et1),
        (arr(h, h), None, arr(n, h), et2),
    )
    acts = ("sigmoid", "softplus")
    out = fused_conv(x, send, recv, mask, n, branches=branches, acts=acts)
    ref = _fused_ref((2, acts), n, x, send, recv, mask, branches, None)
    scale_ref = float(jnp.abs(ref).max()) or 1.0
    assert float(jnp.abs(out - ref).max()) / scale_ref < 1e-4


def pytest_mlp_vjp_matches_reference_ad(case, monkeypatch):
    """grads wrt x, W, b, rtab, scale: the hand-written backward vs
    plain AD of the reference composition."""
    monkeypatch.setenv("HYDRAGNN_PALLAS", "interpret")
    x, send, recv, mask, n = case
    h = x.shape[1]
    rng = np.random.default_rng(3)
    W = jnp.asarray((rng.normal(size=(h, h)) * 0.1).astype(np.float32))
    b = jnp.asarray((rng.normal(size=(h,)) * 0.1).astype(np.float32))
    rt = jnp.asarray((rng.normal(size=(n, h)) * 0.1).astype(np.float32))
    sc = jnp.asarray((rng.normal(size=(send.shape[0], h))).astype(np.float32))

    def loss_fused(x, W, b, rt, sc):
        o = fused_conv(
            x, send, recv, mask, n,
            branches=((W, b, rt, None),), acts=("sigmoid",), scale=sc,
        )
        return (o * o).sum()

    def loss_ref(x, W, b, rt, sc):
        o = _fused_ref(
            (1, ("sigmoid",)), n, x, send, recv, mask, ((W, b, rt, None),), sc
        )
        return (o * o).sum()

    g1 = jax.grad(loss_fused, argnums=(0, 1, 2, 3, 4))(x, W, b, rt, sc)
    g2 = jax.grad(loss_ref, argnums=(0, 1, 2, 3, 4))(x, W, b, rt, sc)
    for a, bb, name in zip(g1, g2, ("x", "W", "b", "rtab", "scale")):
        denom = float(jnp.abs(bb).max()) or 1.0
        rel = float(jnp.abs(a - bb).max()) / denom
        assert rel < 1e-4, f"grad {name} rel err {rel}"


def pytest_identity_vjp_and_narrow_width(monkeypatch):
    """Narrow (non-128) widths lane-pad into the kernel; identity-mode
    VJP (the GIN/SAGE/MFC aggregation backward) matches AD."""
    monkeypatch.setenv("HYDRAGNN_PALLAS", "interpret")
    rng = np.random.default_rng(4)
    e, n, h = 520, 70, 40
    recv = jnp.asarray(np.sort(rng.integers(0, n, e)).astype(np.int32))
    send = jnp.asarray(rng.integers(0, n, e).astype(np.int32))
    mask = jnp.asarray(rng.random(e) > 0.25)
    x = jnp.asarray(rng.normal(size=(n, h)).astype(np.float32))
    out = fused_conv(x, send, recv, mask, n)
    assert out.shape == (n, h)
    ref = _fused_ref((0, ()), n, x, send, recv, mask, (), None)
    np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-4)
    g1 = jax.grad(lambda x: (fused_conv(x, send, recv, mask, n) ** 2).sum())(x)
    g2 = jax.grad(
        lambda x: (_fused_ref((0, ()), n, x, send, recv, mask, (), None) ** 2).sum()
    )(x)
    np.testing.assert_allclose(g1, g2, rtol=1e-3, atol=1e-3)


def pytest_all_masked_is_zero(case, monkeypatch):
    """With every edge masked, even a biased+activated edge network must
    contribute exactly nothing (act(b) != 0 — the mask gates it)."""
    monkeypatch.setenv("HYDRAGNN_PALLAS", "interpret")
    x, send, recv, _, n = case
    h = x.shape[1]
    rng = np.random.default_rng(5)
    W = jnp.asarray((rng.normal(size=(h, h)) * 0.1).astype(np.float32))
    b = jnp.asarray(np.ones((h,), np.float32))
    out = fused_conv(
        x, send, recv, jnp.zeros(send.shape[0], bool), n,
        branches=((W, b, None, None),), acts=("softplus",),
    )
    assert float(jnp.abs(out).max()) == 0.0


def pytest_xla_fallback_is_differentiable(case):
    """Knob=0 (no kernel anywhere) must route through the same custom
    VJP and produce matching grads — the CPU production path."""
    import os

    os.environ["HYDRAGNN_PALLAS"] = "0"
    try:
        x, send, recv, mask, n = case
        out = fused_conv(x, send, recv, mask, n)
        ref = _fused_ref((0, ()), n, x, send, recv, mask, (), None)
        np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-5)
        g = jax.grad(lambda x: (fused_conv(x, send, recv, mask, n) ** 2).sum())(x)
        assert np.isfinite(np.asarray(g)).all()
    finally:
        os.environ.pop("HYDRAGNN_PALLAS", None)


def pytest_model_level_fused_matches_unfused(monkeypatch):
    """GIN / CGCNN / SchNet forward + grads: Architecture.fused_conv
    through the real chassis (interpret kernel) vs the composed legacy
    path — same params, same batch."""
    from hydragnn_tpu.data.ingest import prepare_dataset
    from hydragnn_tpu.data.loader import GraphLoader
    from hydragnn_tpu.data.synthetic import deterministic_graph_data
    from hydragnn_tpu.flagship import flagship_config
    from hydragnn_tpu.models.base import model_loss
    from hydragnn_tpu.models.create import create_model_config
    from hydragnn_tpu.utils.config import update_config

    for model_type in ("GIN", "CGCNN", "SchNet"):
        cfg = flagship_config(hidden_dim=8, num_conv_layers=2, batch_size=4)
        arch = cfg["NeuralNetwork"]["Architecture"]
        arch["model_type"] = model_type
        if model_type == "SchNet":
            arch["num_gaussians"] = 8
            arch["num_filters"] = 8
        samples = deterministic_graph_data(
            number_configurations=8,
            unit_cell_x_range=(2, 3),
            unit_cell_y_range=(2, 3),
            unit_cell_z_range=(2, 3),
            seed=0,
        )
        train, val, test, _, _ = prepare_dataset(samples, cfg)
        cfg = update_config(cfg, train, val, test)
        loader = GraphLoader(train, 4, shuffle=False)
        batch = next(iter(loader))
        model, variables = create_model_config(cfg["NeuralNetwork"], batch)

        def loss(params):
            outs = model.apply(
                {"params": params, "batch_stats": variables.get("batch_stats", {})},
                batch,
                train=False,
            )
            total, _ = model_loss(model.cfg, outs, batch)
            return total

        monkeypatch.setenv("HYDRAGNN_PALLAS", "0")
        l0, g0 = jax.value_and_grad(loss)(variables["params"])
        monkeypatch.setenv("HYDRAGNN_PALLAS", "interpret")
        l1, g1 = jax.value_and_grad(loss)(variables["params"])
        assert abs(float(l1) - float(l0)) <= 1e-4 * max(abs(float(l0)), 1.0), model_type
        gmax = max(
            float(jnp.abs(a).max()) for a in jax.tree_util.tree_leaves(g0)
        )
        gerr = max(
            float(jnp.abs(a - b).max())
            for a, b in zip(
                jax.tree_util.tree_leaves(g0), jax.tree_util.tree_leaves(g1)
            )
        )
        assert gerr / max(gmax, 1e-9) < 1e-4, model_type


def pytest_partitioned_fused_edge_sharded_mesh(monkeypatch):
    """The custom_partitioning rule: operands GSPMD-sharded on the edge
    axis run the kernel per shard (contiguous receiver-sorted slices) +
    one psum, matching the unsharded reference — interpret mode on the
    virtual 8-device CPU mesh."""
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    if len(jax.devices()) < 8:
        pytest.skip("needs the virtual 8-device mesh")
    rng = np.random.default_rng(21)
    e, h, n = 1024, 128, 96  # e divisible by 8
    x = jnp.asarray(rng.normal(size=(n, h)).astype(np.float32))
    recv = jnp.asarray(np.sort(rng.integers(0, n, e)).astype(np.int32))
    send = jnp.asarray(rng.integers(0, n, e).astype(np.int32))
    mask = jnp.asarray(rng.random(e) > 0.25)
    ref = _fused_ref((0, ()), n, x, send, recv, mask, (), None)

    mesh = Mesh(np.array(jax.devices()[:8]), ("edge",))
    esh = NamedSharding(mesh, P("edge"))
    x_s = jax.device_put(x, NamedSharding(mesh, P(None, None)))
    send_s = jax.device_put(send, esh)
    recv_s = jax.device_put(recv, esh)
    mask_s = jax.device_put(mask, esh)

    monkeypatch.setenv("HYDRAGNN_PALLAS", "interpret")
    out = jax.jit(lambda x, s, r, m: fused_conv(x, s, r, m, n))(
        x_s, send_s, recv_s, mask_s
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-4, atol=1e-4)


def pytest_fused_inside_shard_map(monkeypatch):
    """Inside shard_map (the DP train step) operands are already local;
    the partitioned fused op must lower to the plain kernel per device."""
    from hydragnn_tpu.utils.jax_compat import shard_map
    from jax.sharding import Mesh, PartitionSpec as P

    if len(jax.devices()) < 4:
        pytest.skip("needs the virtual multi-device mesh")
    rng = np.random.default_rng(23)
    d_dev, e, h, n = 4, 256, 128, 40
    x = rng.normal(size=(d_dev, n, h)).astype(np.float32)
    recv = np.sort(rng.integers(0, n, (d_dev, e)), axis=1).astype(np.int32)
    send = rng.integers(0, n, (d_dev, e)).astype(np.int32)

    mesh = Mesh(np.array(jax.devices()[:d_dev]), ("data",))
    monkeypatch.setenv("HYDRAGNN_PALLAS", "interpret")

    def local(x, s, r):
        out = fused_conv(
            x[0], s[0], r[0], jnp.ones((e,), bool), n
        )
        return out[None]

    fn = jax.jit(
        shard_map(
            local, mesh=mesh, in_specs=(P("data"), P("data"), P("data")),
            out_specs=P("data"), check_vma=False,
        )
    )
    out = fn(jnp.asarray(x), jnp.asarray(send), jnp.asarray(recv))
    for i in range(d_dev):
        ref = _fused_ref(
            (0, ()), n, jnp.asarray(x[i]), jnp.asarray(send[i]),
            jnp.asarray(recv[i]), jnp.ones((e,), bool), (), None,
        )
        np.testing.assert_allclose(
            np.asarray(out[i]), np.asarray(ref), rtol=1e-4, atol=1e-4
        )


def pytest_fused_conv_validates_inputs():
    x = jnp.zeros((4, 8))
    ids = jnp.zeros((3,), jnp.int32)
    mask = jnp.ones((3,), bool)
    with pytest.raises(ValueError, match="activations"):
        fused_conv(x, ids, ids, mask, 4, branches=((jnp.zeros((8, 8)), None, None, None),))
    with pytest.raises(ValueError, match="at most 2"):
        fused_conv(
            x, ids, ids, mask, 4,
            branches=tuple((jnp.zeros((8, 8)), None, None, None) for _ in range(3)),
            acts=("relu",) * 3,
        )
    with pytest.raises(ValueError, match="activation"):
        fused_conv(
            x, ids, ids, mask, 4,
            branches=((jnp.zeros((8, 8)), None, None, None),), acts=("nope",),
        )
