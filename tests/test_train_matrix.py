"""Full train-to-accuracy matrix: every conv flavor x head config, plus
edge-length-feature and vector-output variants (reference:
tests/test_graphs.py:174-192 parametrization and :135-139 tightened
edge-feature thresholds).

Heavy (each case trains 40 epochs) — gated behind HYDRAGNN_FULL_MATRIX=1
so the default CI pass stays fast; the fast subset lives in
tests/test_train_e2e.py.
"""

import os

import numpy as np
import pytest

from hydragnn_tpu.api import run_prediction, run_training
from hydragnn_tpu.data.synthetic import write_lsms_files

from tests.test_train_e2e import THRESHOLDS, make_config, unittest_train_model

pytestmark = pytest.mark.skipif(
    os.environ.get("HYDRAGNN_FULL_MATRIX", "0") != "1",
    reason="full matrix is gated behind HYDRAGNN_FULL_MATRIX=1",
)

ALL_MODELS = ["SAGE", "GIN", "GAT", "MFC", "PNA", "CGCNN", "SchNet"]

# tightened thresholds with edge-length features (tests/test_graphs.py:135-139)
LENGTH_THRESHOLDS = {
    "PNA": [0.10, 0.10],
    "CGCNN": [0.175, 0.175],
    "SchNet": [0.20, 0.20],
}


def _with_lengths(config):
    config["NeuralNetwork"]["Architecture"]["edge_features"] = ["lengths"]


# The matrix runs at the reference's training budget (ci.json: 100
# epochs @ lr 0.02, batch 32) — several flavors (CGCNN's 1-channel conv,
# MFC, SchNet's nodal heads, PNA's tightened edge-feature thresholds)
# need it.
_EPOCHS = 100


def _ref_budget(config):
    config["NeuralNetwork"]["Training"]["Optimizer"]["learning_rate"] = 0.02
    config["NeuralNetwork"]["Training"]["batch_size"] = 32


def _ref_budget_with_lengths(config):
    _ref_budget(config)
    _with_lengths(config)


@pytest.mark.parametrize("model_type", ALL_MODELS)
def pytest_matrix_singlehead(model_type, tmp_path):
    unittest_train_model(
        model_type, False, tmp_path, num_epoch=_EPOCHS, mutate=_ref_budget
    )


@pytest.mark.parametrize("model_type", ALL_MODELS)
def pytest_matrix_multihead(model_type, tmp_path):
    # Every flavor — SchNet included — runs at the reference thresholds.
    # (r04 relaxed SchNet to 0.45/0.35 on an "identity head information
    # floor" theory; r05 falsified it: the floor was a CAPACITY artifact
    # of running CFConv at 8 filters where the reference cell uses 126 —
    # with parity capacity the cell trains to ~0.03 RMSE / 0.12 MAE,
    # well under 0.2/0.2. The 2-hop backscatter pathway i->j->i carries
    # the node's own type back to it; it just needs filter width.)
    unittest_train_model(
        model_type, True, tmp_path, num_epoch=_EPOCHS, mutate=_ref_budget
    )


@pytest.mark.parametrize("model_type", ["PNA", "CGCNN", "SchNet"])
def pytest_matrix_edge_lengths(model_type, tmp_path):
    unittest_train_model(
        model_type,
        False,
        tmp_path,
        num_epoch=_EPOCHS,
        mutate=_ref_budget_with_lengths,
        thresholds=LENGTH_THRESHOLDS[model_type],
    )


def pytest_matrix_vector_output(tmp_path):
    """Node-level VECTOR head (dim 2) through the raw-file column path
    (reference: pytest_train_model_vectoroutput, tests/test_graphs.py:
    189-192, thresholds 0.2/0.15): predict (out_x2, out_x3) jointly from
    the node type."""
    data_dir = tmp_path / "lsms"
    write_lsms_files(str(data_dir), number_configurations=300, seed=0)

    config = make_config("PNA", False, str(tmp_path), num_epoch=40)
    config["Dataset"]["path"] = {"total": str(data_dir)}
    # raw file rows: feature idx x y z out_x out_x2 out_x3 (cols 0..7);
    # block 2 selects the (out_x2, out_x3) vector
    config["Dataset"]["node_features"] = {
        "name": ["atom_type", "out_x", "x2x3_vec"],
        "dim": [1, 1, 2],
        "column_index": [0, 5, 6],
    }
    voi = config["NeuralNetwork"]["Variables_of_interest"]
    voi["input_node_features"] = [0]
    voi["output_names"] = ["x2x3_vec"]
    voi["output_index"] = [2]
    voi["type"] = ["node"]
    config["NeuralNetwork"]["Architecture"]["task_weights"] = [1.0]

    log_dir = str(tmp_path) + "/logs/"
    run_training(config, log_dir=log_dir)

    config2 = {**config}
    error, error_rmse_task, true_values, predicted_values = run_prediction(
        config2, log_dir=log_dir
    )
    assert float(error_rmse_task[0]) < 0.2
    mae = float(np.mean(np.abs(true_values[0] - predicted_values[0])))
    assert mae < 0.15
    assert true_values[0].shape[-1] == 2  # genuinely a vector head


def pytest_matrix_schnet_inforward_radius(tmp_path):
    """SchNet with the in-forward interaction graph (the reference's
    RadiusInteractionGraph mode, SCFStack.py:63-76) trains to the same
    thresholds as the precomputed-edge path."""

    def mutate(config):
        _ref_budget(config)
        config["NeuralNetwork"]["Architecture"]["radius_graph_in_forward"] = True

    unittest_train_model(
        "SchNet", False, tmp_path, num_epoch=_EPOCHS, mutate=mutate
    )
