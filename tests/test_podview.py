"""Pod-visibility plane tests (hydragnn_tpu/obs/podview.py): per-host
flight shard naming and artifact-collision pinning, the merge reader's
torn-tail / missing-host / duplicate tolerance, SkewMonitor math +
gauges + report schema (runtime AND lint mirrors), the step_skew /
host_stall trigger rules, straggler injection parsing, the
scaling-model skew-tolerance coupling, and per-host Chrome tracks."""

import json
import os

import pytest

from hydragnn_tpu.obs import podview
from hydragnn_tpu.obs.flight import (
    FlightRecorder,
    flight_record_warnings,
    validate_flight_record,
)
from hydragnn_tpu.obs.podview import (
    SkewMonitor,
    collective_attribution,
    host_artifact_path,
    host_epoch_table,
    host_flight_path,
    host_identity,
    list_host_shards,
    load_skew_tolerance,
    merge_host_flights,
    straggler_spec,
    validate_podview_report,
)
from hydragnn_tpu.obs.registry import MetricsRegistry

_MANIFEST = {
    "run": "podtest",
    "mode": "train",
    "jax_version": "0",
    "backend": "cpu",
    "device_kind": "cpu",
    "num_processes": 2,
    "config": {},
}


def _write_shard(base_dir, host, epochs, run_id="rid", slow_epochs=(),
                 slow_s=0.5, data_wait_s=0.01, torn=False):
    """One simulated host's shard: run_start + host_epoch rows (+ a
    torn final line when asked)."""
    path = host_flight_path(str(base_dir), host)
    fr = FlightRecorder(path, enabled=True, host=host)
    fr.start_run(dict(_MANIFEST))
    for ep in range(epochs):
        fr.record(
            "host_epoch",
            epoch=ep,
            host=host,
            run_id=run_id,
            hosts=2,
            epoch_s=1.0 + (slow_s if ep in slow_epochs else 0.0),
            data_wait_s=data_wait_s,
            steps=4,
            nonfinite_skipped=0,
            mfu=0.11 + host / 100.0,
        )
    fr.end_run(status="completed")
    if torn:
        with open(path, "a") as f:
            f.write('{"v": 2, "kind": "host_ep')  # crashed mid-append
    return path


# -- shard naming + artifact collisions --------------------------------------


def test_host_flight_path_naming(tmp_path):
    assert host_flight_path(str(tmp_path), 0).endswith("/flight.jsonl")
    assert host_flight_path(str(tmp_path), 3).endswith("/flight.host3.jsonl")
    _write_shard(tmp_path, 0, 1)
    _write_shard(tmp_path, 2, 1)
    shards = list_host_shards(str(tmp_path))
    assert sorted(shards) == [0, 2]
    assert shards[0].endswith("flight.jsonl")
    assert shards[2].endswith("flight.host2.jsonl")


def test_host_artifact_path_pins_prom_collision():
    # satellite: two hosts sharing a prometheus textfile dir must not
    # clobber each other; rank 0 keeps the legacy name
    assert host_artifact_path("/x/train.prom", 0) == "/x/train.prom"
    assert host_artifact_path("/x/train.prom", 2) == "/x/train.host2.prom"
    assert host_artifact_path("/x/serve_probe.prom", 1) == (
        "/x/serve_probe.host1.prom"
    )


def test_host_identity_knob_overrides(monkeypatch):
    monkeypatch.setenv("HYDRAGNN_PODVIEW_HOST", "3")
    monkeypatch.setenv("HYDRAGNN_PODVIEW_HOSTS", "8")
    assert host_identity() == (3, 8)
    # hosts never reported below host+1 even when the knobs disagree
    monkeypatch.setenv("HYDRAGNN_PODVIEW_HOSTS", "2")
    assert host_identity() == (3, 4)
    assert podview.podview_enabled()


def test_resolve_run_id(monkeypatch):
    assert podview.resolve_run_id("fallback") == "fallback"
    monkeypatch.setenv("HYDRAGNN_PODVIEW_RUN_ID", "shared-id")
    assert podview.resolve_run_id("fallback") == "shared-id"


# -- merge reader ------------------------------------------------------------


def test_merge_stamps_hosts_and_validates(tmp_path):
    _write_shard(tmp_path, 0, 2)
    _write_shard(tmp_path, 1, 2)
    merged = merge_host_flights(str(tmp_path))
    assert merged.hosts == [0, 1]
    assert merged.problems == []
    # every merged event carries its host; host_epoch joins on epoch
    assert all("host" in ev for ev in merged.events)
    table = host_epoch_table(merged.events, run_id="rid")
    assert sorted(table) == [0, 1]
    assert sorted(table[0]) == [0, 1]
    # the merged timeline is schema-valid, and the new host field is an
    # ordinary extra field: no forward-compat warnings
    assert validate_flight_record(merged.events) == []
    assert flight_record_warnings(merged.events) == []


def test_merge_tolerates_torn_tail(tmp_path):
    _write_shard(tmp_path, 0, 2)
    _write_shard(tmp_path, 1, 2, torn=True)
    merged = merge_host_flights(str(tmp_path))
    assert merged.hosts == [0, 1]
    assert any("torn tail" in p for p in merged.problems)
    # the readable prefix of the torn shard still merged
    assert len(host_epoch_table(merged.events)[1]) == 2
    assert validate_flight_record(merged.events) == []


def test_merge_reports_missing_host(tmp_path):
    # host_epoch events promise hosts=2 but only host 0 wrote a shard
    _write_shard(tmp_path, 0, 2)
    merged = merge_host_flights(str(tmp_path))
    assert merged.hosts == [0]
    assert any("missing host shard(s): [1]" in p for p in merged.problems)
    # advisory, never fatal: the single-host timeline still validates
    assert validate_flight_record(merged.events) == []


def test_merge_flags_duplicate_run_id_epoch(tmp_path):
    path = _write_shard(tmp_path, 1, 1)
    fr = FlightRecorder(path, enabled=True, host=1)
    fr.record("host_epoch", epoch=0, host=1, run_id="rid", hosts=1,
              epoch_s=2.0)
    merged = merge_host_flights(str(tmp_path))
    assert any("duplicate host_epoch" in p for p in merged.problems)


def test_merge_accepts_explicit_paths_and_single_file(tmp_path):
    p0 = _write_shard(tmp_path, 0, 1)
    p1 = _write_shard(tmp_path, 1, 1)
    merged = merge_host_flights([p0, p1])
    assert merged.hosts == [0, 1]
    single = merge_host_flights(p1)
    assert single.hosts == [1]


# -- skew monitor ------------------------------------------------------------


def test_skew_monitor_math_gauges_and_report(tmp_path):
    # host 1's epoch 1 runs 0.5s long: skew = 0.5 / 1.5
    _write_shard(tmp_path, 1, 2, slow_epochs=(1,), slow_s=0.5)
    reg = MetricsRegistry(enabled=True, rank=0)
    mon = SkewMonitor(str(tmp_path), host=0, hosts=2, run_id="rid",
                      registry=reg, threshold=0.2)
    own = {"epoch_s": 1.0, "data_wait_s": 0.01, "mfu": 0.11}
    skew0 = mon.observe_epoch(0, dict(own, epoch=0))
    assert skew0 is not None and skew0["skew_frac"] == 0.0
    skew1 = mon.observe_epoch(1, dict(own, epoch=1))
    assert skew1["slowest_host"] == 1
    assert skew1["skew_frac"] == pytest.approx(0.5 / 1.5, abs=1e-6)
    assert skew1["cause"] == "host_slow"
    assert reg.gauge("podview.skew_frac").value == skew1["skew_frac"]
    assert reg.gauge("podview.slowest_host").value == 1.0
    assert reg.gauge("podview.host1.mfu").value == pytest.approx(0.12)
    # the sidecar body passes the runtime validator AND the package-free
    # lint mirror
    report = mon.report()
    assert validate_podview_report(report) == []
    from hydragnn_tpu.lint.artifacts import _check_podview_report

    assert _check_podview_report(json.loads(json.dumps(report))) == []
    assert report["slowest_host"] == 1
    assert len(report["history"]) == 2
    assert mon.overhead_s > 0.0


def test_skew_monitor_data_wait_attribution(tmp_path):
    # the slowest host spent the excess waiting on data, not computing
    _write_shard(tmp_path, 1, 1, slow_epochs=(0,), slow_s=0.5,
                 data_wait_s=0.4)
    mon = SkewMonitor(str(tmp_path), host=0, hosts=2, run_id="rid",
                      threshold=0.2)
    skew = mon.observe_epoch(
        0, {"epoch": 0, "epoch_s": 1.0, "data_wait_s": 0.0}
    )
    assert skew["cause"] == "data_wait"


def test_skew_monitor_single_host_returns_none(tmp_path):
    reg = MetricsRegistry(enabled=True, rank=0)
    mon = SkewMonitor(str(tmp_path), host=0, hosts=1, run_id="rid",
                      registry=reg)
    assert mon.observe_epoch(0, {"epoch_s": 1.0}) is None
    assert reg.gauge("podview.skew_frac").value == 0.0
    assert reg.gauge("podview.slowest_host").value == -1.0


def test_skew_monitor_stall_age_for_silent_peer(tmp_path):
    # a peer that never writes counts as stalled from monitor birth
    reg = MetricsRegistry(enabled=True, rank=0)
    mon = SkewMonitor(str(tmp_path), host=0, hosts=2, run_id="rid",
                      registry=reg)
    mon._t0 -= 100.0
    mon.observe_epoch(0, {"epoch_s": 1.0})
    assert reg.gauge("podview.stall_age_s").value >= 100.0


def test_skew_monitor_never_raises(tmp_path, monkeypatch):
    mon = SkewMonitor(str(tmp_path), host=0, hosts=2)
    monkeypatch.setattr(
        podview, "list_host_shards",
        lambda *_: (_ for _ in ()).throw(RuntimeError("fs exploded")),
    )
    assert mon.observe_epoch(0, {"epoch_s": 1.0}) is None  # degraded, alive


# -- trigger rules -----------------------------------------------------------


def test_step_skew_and_host_stall_trigger_rules():
    from hydragnn_tpu.obs.triggers import (
        RULE_KINDS,
        TriggerEngine,
        TriggerRule,
    )

    assert "step_skew" in RULE_KINDS and "host_stall" in RULE_KINDS
    reg = MetricsRegistry(enabled=True, rank=0)
    reg.gauge("podview.skew_frac").set(0.6)
    reg.gauge("podview.stall_age_s").set(10.0)
    reg.gauge("podview.slowest_host").set(3.0)
    eng = TriggerEngine(
        [
            TriggerRule("skew", "step_skew", "podview.skew_frac", 0.25),
            TriggerRule("stall", "host_stall", "podview.stall_age_s", 120.0),
        ],
        registry=reg,
        cooldown_s=0.0,
    )
    fired = eng.evaluate()
    assert [v.kind for v in fired] == ["step_skew"]
    assert fired[0].detail["slowest_host"] == 3  # names the blamed host
    # below threshold: quiet
    reg.gauge("podview.skew_frac").set(0.1)
    assert eng.evaluate() == []


def test_incident_bundle_carries_podview_evidence(tmp_path, monkeypatch):
    from hydragnn_tpu.utils import profile

    monkeypatch.setattr(profile, "try_start_capture", lambda prefix: False)
    from hydragnn_tpu.obs.triggers import (
        IncidentRecorder,
        TriggerVerdict,
        validate_incident_manifest,
    )

    run_dir = tmp_path / "run"
    run_dir.mkdir()
    _write_shard(run_dir, 0, 1)
    _write_shard(run_dir, 1, 1, slow_epochs=(0,), slow_s=1.0)
    mon = SkewMonitor(str(run_dir), host=0, hosts=2, run_id="rid",
                      threshold=0.2)
    mon.observe_epoch(0, {"epoch": 0, "epoch_s": 1.0, "data_wait_s": 0.0})
    rec = IncidentRecorder(str(tmp_path / "incidents"), podview=mon)
    verdict = TriggerVerdict(
        "skew", "step_skew", "podview.skew_frac", 0.5, 0.2, 1.0,
        detail={"slowest_host": 1},
    )
    inc = rec.open_incident(verdict)
    rec.tick()
    rec.tick()
    rec.tick()
    assert rec.open is None  # closed
    sidecar = os.path.join(inc.dir, "podview_report.json")
    with open(sidecar) as f:
        report = json.load(f)
    assert validate_podview_report(report) == []
    assert report["slowest_host"] == 1  # names the offending host
    # per-host evidence: the peer shard's tail rides along
    assert os.path.exists(os.path.join(inc.dir, "flight_tail.host1.jsonl"))
    with open(os.path.join(inc.dir, "incident_manifest.json")) as f:
        manifest = json.load(f)
    assert validate_incident_manifest(manifest) == []
    assert manifest["kind"] == "step_skew"
    assert manifest["files"]["podview_report"] == "podview_report.json"


# -- straggler injection -----------------------------------------------------


def test_straggler_spec_parsing(monkeypatch):
    assert straggler_spec() is None
    monkeypatch.setenv("HYDRAGNN_INJECT_STRAGGLER", "1:250")
    assert straggler_spec() == (1, 0.25)
    monkeypatch.setenv("HYDRAGNN_INJECT_STRAGGLER", "garbage")
    assert straggler_spec() is None  # malformed degrades to no injection


def test_step_spans_inject_straggler_on_matching_host(monkeypatch):
    monkeypatch.setenv("HYDRAGNN_PODVIEW_HOST", "1")
    monkeypatch.setenv("HYDRAGNN_PODVIEW_HOSTS", "2")
    monkeypatch.setenv("HYDRAGNN_INJECT_STRAGGLER", "1:50")
    from hydragnn_tpu.obs.spans import StepSpans

    spans = StepSpans()
    assert spans._straggle_s == pytest.approx(0.05)
    snap = spans.epoch_snapshot()
    assert snap["process_index"] == 1
    assert snap["process_count"] == 2
    # the other host does not sleep
    monkeypatch.setenv("HYDRAGNN_PODVIEW_HOST", "0")
    assert StepSpans()._straggle_s == 0.0


# -- scaling-model coupling --------------------------------------------------


def test_load_skew_tolerance_committed_and_fallback(tmp_path, monkeypatch):
    # the committed estimate at the repo root carries the block
    assert load_skew_tolerance() == pytest.approx(0.2)
    # absent block -> conservative fallback
    bare = tmp_path / "SCALING_est_r99.json"
    bare.write_text(json.dumps({"mesh": [1]}))
    assert load_skew_tolerance(str(bare)) == podview.DEFAULT_SKEW_THRESHOLD
    # knob override wins over the model derivation
    monkeypatch.setenv("HYDRAGNN_PODVIEW_SKEW", "0.4")
    assert podview.default_skew_threshold() == pytest.approx(0.4)


def test_scaling_estimate_skew_tolerance_block():
    import ast

    src = open(
        os.path.join(os.path.dirname(__file__), "..", "tools",
                     "scaling_estimate.py")
    ).read()
    tree = ast.parse(src)
    fn = next(
        n for n in tree.body
        if isinstance(n, ast.FunctionDef) and n.name == "skew_tolerance_block"
    )
    ns = {}
    exec(compile(ast.Module(body=[fn], type_ignores=[]), "se", "exec"), ns)
    block = ns["skew_tolerance_block"](
        {"8": {"dp_efficiency_no_overlap": 0.9}, "x": {}}
    )
    assert block["per_width"]["8"]["skew_frac_threshold"] == pytest.approx(0.4)
    assert "x" not in block["per_width"]
    assert 0.2 <= block["default_step_skew_threshold"] <= 0.5
    # the COMMITTED estimate carries the same block the monitor reads
    root = os.path.join(os.path.dirname(__file__), "..")
    with open(os.path.join(root, "SCALING_est_r06.json")) as f:
        rec = json.load(f)
    assert rec["skew_tolerance"] == ns["skew_tolerance_block"](rec["widths"])


def test_collective_attribution_models_wire_share():
    scaling = {
        "step_ms_device_single_chip": 80.0,
        "ici_gbps_assumed": 45.0,
        "param_bytes_f32": 4.0e6,
    }
    out = collective_attribution(
        {"available": True, "data": 4, "fsdp": 1,
         "params": {"bytes_global": 4.0e6}},
        scaling,
    )
    assert out["modeled"]
    # ring all-reduce: 2(n-1)/n * 4MB at 45 GB/s
    expect_ms = 2 * 3 / 4 * 4.0e6 / 45e9 * 1e3
    assert out["wire_ms"] == pytest.approx(expect_ms, rel=1e-3)
    assert 0.0 < out["wire_frac"] < 0.01
    fsdp = collective_attribution(
        {"available": True, "data": 4, "fsdp": 2,
         "params": {"bytes_global": 4.0e6}},
        scaling,
    )
    assert fsdp["wire_ms"] > out["wire_ms"]  # ag/rs traffic adds wire
    off = collective_attribution(None, scaling)
    assert not off["modeled"]


# -- chrome export -----------------------------------------------------------


def test_chrome_export_one_track_per_host(tmp_path):
    from hydragnn_tpu.obs.trace import export_flight_chrome, flight_to_chrome

    _write_shard(tmp_path, 0, 2)
    _write_shard(tmp_path, 1, 2)
    merged = merge_host_flights(str(tmp_path))
    events = flight_to_chrome(merged.events)["traceEvents"]
    host_spans = [
        e for e in events
        if e.get("ph") == "X" and str(e.get("name", "")).startswith("host")
    ]
    assert {e["tid"] for e in host_spans} == {0, 1}
    names = [
        e for e in events
        if e.get("ph") == "M" and e.get("name") == "thread_name"
    ]
    assert {e["args"]["name"] for e in names} == {"host 0", "host 1"}
    # the exporter accepts a run DIRECTORY and stitches it itself
    out = tmp_path / "trace.json"
    export_flight_chrome(str(tmp_path), str(out))
    data = json.loads(out.read_text())["traceEvents"]
    assert any(str(e.get("name", "")).startswith("host1") for e in data)
