"""Raw-GDB9 file-format path, end to end (VERDICT r02 item 6).

Every prior example run exercised only the synthetic fallback; this
drives examples/qm9 against the checked-in GDB9-format fixture
(tests/data/gdb9_fixture — see its README: real CHNOF species,
idealized geometries, surrogate properties, exact file format including
Fortran ``*^`` floats), so the raw-data parser path has a recorded
artifact (reference behavior matched: examples/qm9/qm9.py:56-58
upstream reads the same files through torch_geometric's QM9 loader).
"""

import os
import shutil
import subprocess
import sys

import numpy as np

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_FIXTURE = os.path.join(_REPO, "tests", "data", "gdb9_fixture")


def _load_example():
    sys.path.insert(0, os.path.join(_REPO, "examples", "qm9"))
    try:
        import qm9 as qm9_example  # noqa
    finally:
        sys.path.pop(0)
    return qm9_example


def pytest_gdb9_parser_reads_fixture():
    """Every fixture file parses: correct atom counts, CHNOF elements,
    finite geometry, the G column lands in graph_y — including files
    using the Fortran ``*^`` float notation."""
    qm9_example = _load_example()
    files = sorted(f for f in os.listdir(_FIXTURE) if f.endswith(".xyz"))
    assert len(files) == 100
    n_fortran = 0
    for f in files:
        path = os.path.join(_FIXTURE, f)
        with open(path) as fh:
            text = fh.read()
        n_fortran += "*^" in text
        s = qm9_example.read_gdb9_xyz(path)
        n = int(open(path).readline().split()[0])
        assert s.x.shape == (n, 1)
        assert set(np.asarray(s.x[:, 0], np.int64)) <= {1, 6, 7, 8, 9}
        assert s.pos.shape == (n, 3) and np.isfinite(s.pos).all()
        assert s.graph_y.shape == (1,) and np.isfinite(s.graph_y).all()
        # the target is the G column (free energy), a large negative sum
        assert s.graph_y[0] < -30.0
    assert n_fortran >= 20, "fixture must exercise the *^ float path"


def pytest_gdb9_fixture_train_e2e(tmp_path):
    """examples/qm9 ingestion -> train -> predict on the fixture files
    (NOT the synthetic fallback) at a sane threshold, as a subprocess —
    the same harness as tests/test_examples.py."""
    workdir = os.path.join(str(tmp_path), "qm9")
    shutil.copytree(
        os.path.join(_REPO, "examples", "qm9"),
        workdir,
        ignore=shutil.ignore_patterns("dataset", "logs", "__pycache__"),
    )
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=_REPO)
    env.pop("XLA_FLAGS", None)
    ret = subprocess.run(
        [sys.executable, "qm9.py", "--data", _FIXTURE, "--nsamples", "100"],
        cwd=workdir,
        env=env,
        capture_output=True,
        text=True,
        timeout=900,
    )
    assert ret.returncode == 0, f"qm9 fixture run failed:\n{ret.stdout}\n{ret.stderr}"
    assert "read 100 GDB9 molecules" in ret.stdout, ret.stdout
