"""Serving subsystem tests (hydragnn_tpu/serve): bucket routing, the
deadline micro-batcher's contracts (flush-on-full, flush-on-deadline,
bounded-queue rejection, oversize degradation, thread safety), and the
load-bearing acceptance check — bucketed, deadline-batched serving
produces the same predictions as the offline ``run_prediction`` path on
the same graphs and checkpoint.

All CPU (conftest pins the 8-device virtual mesh); servers here are
smoke-sized so the whole file stays tier-1-fast.
"""

import threading
import time
import types

import numpy as np
import pytest

from hydragnn_tpu.serve import (
    MicroBatchQueue,
    ModelRegistry,
    ModelServer,
    Overloaded,
    ServeConfig,
    build_bucket_ladder,
    route,
)


def _sizes(pairs):
    return [types.SimpleNamespace(num_nodes=n, num_edges=e) for n, e in pairs]


# ---------------------------------------------------------------------------
# bucket ladder + routing (no jax needed beyond import)
# ---------------------------------------------------------------------------


def test_bucket_ladder_smallest_fit_routing():
    ref = _sizes([(8, 20), (10, 24), (40, 100), (100, 260)])
    buckets = build_bucket_ladder(ref, max_batch=4, num_buckets=3)
    assert len(buckets) >= 2
    # ascending caps, ascending plans
    for a, b in zip(buckets, buckets[1:]):
        assert a.cap_nodes <= b.cap_nodes and a.node_pad <= b.node_pad
    # any full batch of cap-sized graphs fits its own bucket's plan
    for b in buckets:
        assert b.fits_totals(4 * b.cap_nodes, 4 * b.cap_edges, 4)
    # smallest fitting bucket wins
    assert route(buckets, 8, 20) is buckets[0]
    assert route(buckets, buckets[0].cap_nodes + 1, 1) is not buckets[0]
    big = buckets[-1]
    assert route(buckets, big.cap_nodes, big.cap_edges) is not None
    assert route(buckets, big.cap_nodes + 1, 1) is None  # oversize


def test_bucket_pad_plans_dedup_and_order():
    from hydragnn_tpu.data.loader import bucket_pad_plans

    # one size -> every quantile collapses to a single plan
    plans = bucket_pad_plans(_sizes([(10, 30)] * 5), batch_size=4, num_buckets=3)
    assert len(plans) == 1
    (cap_n, cap_e), (n_pad, e_pad, g_pad) = plans[0]
    assert (cap_n, cap_e) == (10, 30)
    assert n_pad > 4 * 10 and e_pad >= 4 * 30 and g_pad == 5
    with pytest.raises(ValueError):
        bucket_pad_plans([], batch_size=4)


# ---------------------------------------------------------------------------
# micro-batch queue (pure threading, jax-free)
# ---------------------------------------------------------------------------


def test_queue_deadline_then_drain():
    q = MicroBatchQueue(num_buckets=2, max_batch=4, max_delay_s=0.05, max_pending=8)
    q.put(1, "a")
    bucket, reqs, reason = q.take_batch()
    assert (bucket, reason) == (1, "deadline")
    assert [r.item for r in reqs] == ["a"]
    q.put(0, "b")
    q.close()
    with pytest.raises(RuntimeError):
        q.put(0, "c")
    bucket, reqs, reason = q.take_batch()
    assert (bucket, reason) == (0, "drain")
    assert q.take_batch() is None  # drained + closed


def test_queue_full_flush_beats_deadline():
    q = MicroBatchQueue(num_buckets=1, max_batch=2, max_delay_s=30.0, max_pending=8)
    q.put(0, 1)
    q.put(0, 2)
    t0 = time.monotonic()
    bucket, reqs, reason = q.take_batch()
    assert reason == "full" and len(reqs) == 2
    assert time.monotonic() - t0 < 5.0  # did not wait out the 30s deadline


def test_queue_overload():
    q = MicroBatchQueue(num_buckets=1, max_batch=10, max_delay_s=30.0, max_pending=2)
    q.put(0, 1)
    q.put(0, 2)
    with pytest.raises(Overloaded):
        q.put(0, 3)


# ---------------------------------------------------------------------------
# ModelServer over a real (random-init) model
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def served_setup():
    """Smoke-sized PNA multihead + its prepared samples, registered once
    for every server test in the module."""
    from hydragnn_tpu.flagship import build_flagship

    _, model, variables, loader = build_flagship(
        n_samples=24,
        hidden_dim=8,
        num_conv_layers=2,
        batch_size=4,
        unit_cells=(2, 3),
    )
    registry = ModelRegistry()
    served = registry.register("smoke", model, variables)
    return served, list(loader.all_samples)


def _direct_forward(served, sample):
    """Reference prediction: unbatched natural-pad forward, sliced the
    same way the server slices."""
    from hydragnn_tpu.graph.batch import batch_graphs
    from hydragnn_tpu.serve import request_to_dict

    g = request_to_dict(sample)
    batch = batch_graphs([g])
    outputs = served.forward(served.variables, batch)
    cfg = served.cfg
    n = int(np.asarray(g["x"]).shape[0])
    out = {}
    for ihead in range(cfg.num_heads):
        o = np.asarray(outputs[ihead])
        if cfg.output_type[ihead] == "graph":
            out[cfg.output_names[ihead]] = o[0]
        else:
            out[cfg.output_names[ihead]] = o[:n]
    return out


def _assert_result_close(got, want):
    assert sorted(got) == sorted(want)
    for k in want:
        np.testing.assert_allclose(got[k], want[k], rtol=1e-5, atol=1e-6)


def test_deadline_flush_single_request(served_setup):
    served, samples = served_setup
    with ModelServer(
        served, samples, ServeConfig(max_batch=4, max_delay_ms=30.0)
    ) as server:
        t0 = time.monotonic()
        result = server.predict(samples[0], timeout=120)
        elapsed = time.monotonic() - t0
        _assert_result_close(result, _direct_forward(served, samples[0]))
        snap = server.metrics_snapshot()
    # one request alone cannot fill max_batch=4: it flushed on deadline
    flushes = {
        k: v
        for b in snap["buckets"].values()
        for k, v in b.items()
        if k.startswith("flush_") and v
    }
    assert sum(v for k, v in flushes.items() if k == "flush_deadline") == 1
    assert snap["results_total"] == 1
    assert snap["compile_misses"] == 0 and snap["compile_warmup"] >= 1
    assert snap["latency"]["p50_ms"] > 0
    assert elapsed < 60


def test_full_batch_flush_and_occupancy(served_setup):
    served, samples = served_setup
    # deadline far away: completion within the timeout proves flush-on-full
    with ModelServer(
        served, samples, ServeConfig(max_batch=2, max_delay_ms=30_000.0)
    ) as server:
        futs = [server.submit(s) for s in samples[:4]]
        results = [f.result(timeout=120) for f in futs]
        snap = server.metrics_snapshot()
    for s, got in zip(samples[:4], results):
        _assert_result_close(got, _direct_forward(served, s))
    total_full = sum(b.get("flush_full", 0) for b in snap["buckets"].values())
    assert total_full >= 1
    occupied = [b for b in snap["buckets"].values() if b["batches"]]
    assert any(b["occupancy_mean"] == 2.0 for b in occupied)
    assert snap["compile_misses"] == 0


def test_overload_rejection(served_setup):
    served, samples = served_setup
    server = ModelServer(
        served,
        samples,
        # max_batch larger than max_pending and an hour-long deadline:
        # nothing flushes, the bounded queue must reject the overflow
        ServeConfig(max_batch=64, max_delay_ms=3_600_000.0, max_pending=2),
    )
    server.start()
    try:
        f1 = server.submit(samples[0])
        f2 = server.submit(samples[1])
        with pytest.raises(Overloaded):
            server.submit(samples[2])
        assert server.metrics_snapshot()["rejected_overload"] == 1
    finally:
        server.stop()  # drains f1/f2 through the "drain" flush path
    _assert_result_close(f1.result(timeout=10), _direct_forward(served, samples[0]))
    _assert_result_close(f2.result(timeout=10), _direct_forward(served, samples[1]))


def _chain_graph(n_nodes, spec):
    """Synthetic chain-graph request matching a reference sample's field
    spec (feature width, pos/edge_attr presence and dims)."""
    rng = np.random.default_rng(n_nodes)
    g = {
        "x": rng.normal(size=(n_nodes, spec["feat_dim"])).astype(np.float32),
        "senders": np.arange(n_nodes - 1, dtype=np.int32),
        "receivers": np.arange(1, n_nodes, dtype=np.int32),
    }
    if spec["pos_dim"]:
        g["pos"] = rng.normal(size=(n_nodes, spec["pos_dim"])).astype(np.float32)
    if spec["edge_dim"]:
        g["edge_attr"] = rng.normal(size=(n_nodes - 1, spec["edge_dim"])).astype(
            np.float32
        )
    return g


def _spec_of(sample):
    ea = getattr(sample, "edge_attr", None)
    pos = getattr(sample, "pos", None)
    return {
        "feat_dim": int(np.asarray(sample.x).shape[1]),
        "pos_dim": int(np.asarray(pos).shape[1]) if pos is not None else 0,
        "edge_dim": int(np.asarray(ea).shape[-1]) if ea is not None else 0,
    }


def test_oversize_fallbacks(served_setup):
    served, samples = served_setup
    spec = _spec_of(samples[0])
    with ModelServer(
        served, samples, ServeConfig(max_batch=4, max_delay_ms=5.0)
    ) as server:
        big = server.buckets[-1]
        # over the per-graph routing cap, but alone it fits the largest
        # plan -> immediate batch-of-1 on the compiled largest bucket
        n_mid = big.cap_nodes + 1
        assert big.fits_totals(n_mid, n_mid - 1, 1)
        g_mid = _chain_graph(n_mid, spec)
        res_mid = server.predict(g_mid, timeout=120)
        _assert_result_close(res_mid, _direct_forward(served, g_mid))
        snap = server.metrics_snapshot()
        assert snap["oversize_largest_bucket"] == 1
        assert snap["compile_misses"] == 0  # largest bucket was pre-compiled

        # over even the largest plan -> eager natural-pad call, counted
        # as the compile-cache miss it is
        g_huge = _chain_graph(big.node_pad + 5, spec)
        res_huge = server.predict(g_huge, timeout=240)
        _assert_result_close(res_huge, _direct_forward(served, g_huge))
        snap = server.metrics_snapshot()
        assert snap["oversize_eager"] == 1
        assert snap["compile_misses"] == 1

        # eager_fallback disabled -> loud Oversize instead
    with ModelServer(
        served,
        samples,
        ServeConfig(max_batch=4, max_delay_ms=5.0, eager_fallback=False),
    ) as server2:
        from hydragnn_tpu.serve import Oversize

        fut = server2.submit(_chain_graph(server2.buckets[-1].node_pad + 5, spec))
        with pytest.raises(Oversize):
            fut.result(timeout=10)


def test_request_spec_validation(served_setup):
    served, samples = served_setup
    spec = _spec_of(samples[0])
    assert spec["pos_dim"], "flagship samples are expected to carry pos"
    with ModelServer(
        served, samples, ServeConfig(max_batch=2, max_delay_ms=5.0)
    ) as server:
        g = _chain_graph(4, spec)
        del g["pos"]  # flagship samples carry pos; the spec requires it
        with pytest.raises(ValueError, match="pos"):
            server.submit(g)
        g2 = _chain_graph(4, dict(spec, feat_dim=spec["feat_dim"] + 1))
        with pytest.raises(ValueError, match="feature width"):
            server.submit(g2)


def test_two_thread_concurrent_clients(served_setup):
    served, samples = served_setup
    expected = [_direct_forward(served, s) for s in samples[:6]]
    with ModelServer(
        served, samples, ServeConfig(max_batch=4, max_delay_ms=10.0)
    ) as server:
        results = {0: [], 1: []}
        errors = []

        def client(tid):
            try:
                for _ in range(3):
                    for i, s in enumerate(samples[:6]):
                        results[tid].append((i, server.predict(s, timeout=120)))
            except BaseException as exc:  # noqa: BLE001 - assert below
                errors.append(exc)

        threads = [threading.Thread(target=client, args=(t,)) for t in (0, 1)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(300)
        snap = server.metrics_snapshot()
    assert not errors
    for tid in (0, 1):
        assert len(results[tid]) == 18
        for i, got in results[tid]:
            _assert_result_close(got, expected[i])
    assert snap["results_total"] == 36
    assert snap["compile_misses"] == 0  # steady state never recompiled


def test_metrics_snapshot_and_tensorboard_export():
    from hydragnn_tpu.serve import ServeMetrics
    from hydragnn_tpu.utils.tensorboard import write_scalar_dict

    m = ServeMetrics(num_buckets=2)
    m.record_request(0)
    m.record_batch(0, occupancy=3, capacity=4, reason="full")
    m.record_compile(hit=False, warmup=True)
    m.record_compile(hit=True)
    m.observe_latency(0.010)
    m.observe_latency(0.030)
    snap = m.snapshot()
    assert snap["buckets"]["bucket_0"]["occupancy_mean"] == 3.0
    assert snap["compile_warmup"] == 1 and snap["compile_hits"] == 1
    assert 10.0 <= snap["latency"]["p50_ms"] <= 30.0

    class _Rec:
        def __init__(self):
            self.rows = []

        def add_scalar(self, tag, value, step):
            self.rows.append((tag, value, step))

    w = _Rec()
    n = write_scalar_dict(w, snap, step=7, prefix="serve")
    assert n == len(w.rows) and n > 10
    assert all(tag.startswith("serve/") and step == 7 for tag, _, step in w.rows)
    assert ("serve/buckets/bucket_0/occupancy_mean", 3.0, 7) in w.rows


# ---------------------------------------------------------------------------
# acceptance: serve == run_prediction on the same graphs + checkpoint
# ---------------------------------------------------------------------------


def _equiv_config():
    """Fresh config dict per pipeline call (update_config completes it in
    place). PNA multihead: one graph head + node heads exercise both
    result-slicing paths."""
    from hydragnn_tpu.flagship import flagship_config

    # batch 5 is indivisible by the 8-device virtual mesh: both training
    # and prediction take the single-device path (the sharded path has
    # its own equivalence suite), keeping this test about serving
    return flagship_config(hidden_dim=8, num_conv_layers=2, batch_size=5, num_epoch=2)


def test_serve_matches_run_prediction(tmp_path):
    from hydragnn_tpu.api import (
        prepare_loaders_and_config,
        run_prediction,
        run_training,
        serve_model,
    )
    from hydragnn_tpu.data.synthetic import deterministic_graph_data

    log_dir = str(tmp_path) + "/logs/"

    def data():
        return deterministic_graph_data(
            number_configurations=40,
            unit_cell_x_range=(2, 3),
            unit_cell_y_range=(2, 3),
            unit_cell_z_range=(2, 3),
            seed=0,
        )

    model, state, history, _ = run_training(
        _equiv_config(), samples=data(), log_dir=log_dir
    )
    _, _, trues, preds = run_prediction(
        _equiv_config(), samples=data(), log_dir=log_dir
    )

    # the same deterministic pipeline yields run_prediction's test split
    _, _, test_loader, _ = prepare_loaders_and_config(_equiv_config(), data())
    test_samples = list(test_loader.all_samples)
    assert len(test_samples) > 1

    server = serve_model(
        _equiv_config(),
        samples=data(),
        log_dir=log_dir,
        serve_config=ServeConfig(max_batch=4, max_delay_ms=10.0),
    )
    try:
        results = server.predict_many(test_samples, timeout=300)
        snap = server.metrics_snapshot()
    finally:
        server.stop()

    cfg = model.cfg
    for ihead in range(cfg.num_heads):
        name = cfg.output_names[ihead]
        if cfg.output_type[ihead] == "graph":
            served_vals = np.stack([r[name] for r in results])
        else:
            served_vals = np.concatenate([r[name] for r in results])
        assert served_vals.shape == preds[ihead].shape
        np.testing.assert_allclose(
            served_vals,
            preds[ihead],
            rtol=1e-5,
            atol=1e-6,
            err_msg=f"head {name}: bucketed deadline-batched serving diverged "
            "from run_prediction on identical graphs",
        )
    # steady-state contract: every request landed on a pre-compiled bucket
    assert snap["compile_misses"] == 0
    assert snap["results_total"] == len(test_samples)
