"""graftsync: per-HS-rule true-positive / near-miss fixtures, the
annotation grammar and suppression forms, lock-order cycle detection and
the --order-graph export, baseline machinery, the runtime lock-order
witness (HYDRAGNN_LOCK_DEBUG), and regression tests pinning the
concurrency bugs the analyzer's first full-tree run surfaced.

Fixtures are written to tmp_path (outside the repo) so the HS rules'
path policy (tests/ and lint/fixtures are exempt) doesn't mask them;
every run builds a fresh rule set — HS006 accumulates cross-file
lock-order state per scan.
"""

import importlib.util
import json
import os
import textwrap
import threading
import time

import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_cli():
    path = os.path.join(REPO_ROOT, "tools", "graftsync.py")
    spec = importlib.util.spec_from_file_location("_graftsync_cli", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


CLI = _load_cli()
CORE, CONC = CLI._load_lint_pkg()

BASELINE = os.path.join(REPO_ROOT, "tools", "graftsync_baseline.json")


def sync_lint(tmp_path, source, rule_ids=None, name="fixture.py",
              full_tree=False):
    """Write ``source`` to a tmp file and analyze it with fresh rules.
    HS006 only reports from finalize(), which run_lint calls on
    full-tree scans — pass full_tree=True for cycle fixtures."""
    p = tmp_path / name
    p.write_text(textwrap.dedent(source))
    rules = CONC.concurrency_rules(REPO_ROOT)
    if rule_ids:
        rules = [r for r in rules if r.id in set(rule_ids)]
    return CORE.run_lint(
        REPO_ROOT, rules, paths=[str(p)], full_tree=full_tree
    )


# ---------------------------------------------------------------- HS001


class TestUnguardedSharedState:
    def test_flags_undeclared_mutation_in_concurrent_class(self, tmp_path):
        findings = sync_lint(
            tmp_path,
            """
            import threading


            class Box:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._items = []

                def add(self, x):
                    self._items.append(x)
            """,
            ["HS001"],
        )
        assert [f.rule for f in findings] == ["HS001"]
        assert "_items" in findings[0].message

    def test_guarded_access_under_lock_is_clean(self, tmp_path):
        findings = sync_lint(
            tmp_path,
            """
            import threading


            class Box:
                def __init__(self):
                    self._lock = threading.Lock()
                    # graftsync: guarded-by=fixture.Box._lock
                    self._items = []

                def add(self, x):
                    with self._lock:
                        self._items.append(x)
            """,
            ["HS001"],
        )
        assert findings == []

    def test_flags_guarded_access_without_lock(self, tmp_path):
        findings = sync_lint(
            tmp_path,
            """
            import threading


            class Box:
                def __init__(self):
                    self._lock = threading.Lock()
                    # graftsync: guarded-by=fixture.Box._lock
                    self._items = []

                def add(self, x):
                    with self._lock:
                        self._items.append(x)

                def peek(self):
                    return list(self._items)
            """,
            ["HS001"],
        )
        assert [f.rule for f in findings] == ["HS001"]
        assert "without holding" in findings[0].message

    def test_holds_annotation_transfers_the_obligation(self, tmp_path):
        src = """
        import threading


        class Box:
            def __init__(self):
                self._lock = threading.Lock()
                # graftsync: guarded-by=fixture.Box._lock
                self._items = []

            # graftsync: holds=fixture.Box._lock
            def _append(self, x):
                self._items.append(x)

            def add(self, x):
                with self._lock:
                    self._append(x)
        """
        assert sync_lint(tmp_path, src, ["HS001"]) == []

        # calling a holds= method WITHOUT the lock is the violation
        findings = sync_lint(
            tmp_path,
            src
            + "\n            def sneak(self, x):\n"
            "                self._append(x)\n",
            ["HS001"],
        )
        assert any("holds=" in f.message for f in findings)

    def test_flags_unguarded_module_global(self, tmp_path):
        findings = sync_lint(
            tmp_path,
            """
            _COUNT = 0


            def bump():
                global _COUNT
                _COUNT += 1
            """,
            ["HS001"],
        )
        assert [f.rule for f in findings] == ["HS001"]
        assert "_COUNT" in findings[0].message

    def test_thread_safe_declaration_needs_a_reason(self, tmp_path):
        findings = sync_lint(
            tmp_path,
            """
            import threading


            class Box:
                def __init__(self):
                    self._lock = threading.Lock()
                    # graftsync: thread-safe=
                    self._n = 0

                def bump(self):
                    self._n = self._n + 1
            """,
            ["HS001"],
        )
        assert [f.rule for f in findings] == ["HS001"]
        assert "needs a reason" in findings[0].message

    def test_thread_safe_with_reason_is_clean(self, tmp_path):
        findings = sync_lint(
            tmp_path,
            """
            import threading


            class Box:
                def __init__(self):
                    self._lock = threading.Lock()
                    # graftsync: thread-safe=GIL-atomic one-way latch
                    self._n = 0

                def bump(self):
                    self._n = self._n + 1
            """,
            ["HS001"],
        )
        assert findings == []

    def test_lockless_class_is_not_concurrent(self, tmp_path):
        # no lock, no thread targets, no shared annotation: plain object
        findings = sync_lint(
            tmp_path,
            """
            class Plain:
                def __init__(self):
                    self._items = []

                def add(self, x):
                    self._items.append(x)
            """,
            ["HS001"],
        )
        assert findings == []

    def test_shared_annotation_makes_a_class_concurrent(self, tmp_path):
        findings = sync_lint(
            tmp_path,
            """
            # graftsync: shared
            class Shared:
                def __init__(self):
                    self._items = []

                def add(self, x):
                    self._items.append(x)
            """,
            ["HS001"],
        )
        assert [f.rule for f in findings] == ["HS001"]


# ---------------------------------------------------------------- HS002


class TestAcquireWithoutRelease:
    def test_flags_bare_acquire(self, tmp_path):
        findings = sync_lint(
            tmp_path,
            """
            import threading

            _L = threading.Lock()


            def f(work):
                _L.acquire()
                work()
                _L.release()
            """,
            ["HS002"],
        )
        assert [f.rule for f in findings] == ["HS002"]
        assert "finally" in findings[0].message

    def test_try_finally_release_is_clean(self, tmp_path):
        findings = sync_lint(
            tmp_path,
            """
            import threading

            _L = threading.Lock()


            def f(work):
                _L.acquire()
                try:
                    work()
                finally:
                    _L.release()
            """,
            ["HS002"],
        )
        assert findings == []

    def test_with_statement_is_clean(self, tmp_path):
        findings = sync_lint(
            tmp_path,
            """
            import threading

            _L = threading.Lock()


            def f(work):
                with _L:
                    work()
            """,
            ["HS002"],
        )
        assert findings == []


# ---------------------------------------------------------------- HS003


class TestBlockingCallUnderLock:
    def test_flags_sleep_under_lock(self, tmp_path):
        findings = sync_lint(
            tmp_path,
            """
            import threading
            import time

            _L = threading.Lock()


            def f():
                with _L:
                    time.sleep(0.1)
            """,
            ["HS003"],
        )
        assert [f.rule for f in findings] == ["HS003"]
        assert "sleep" in findings[0].message

    def test_sleep_outside_lock_is_clean(self, tmp_path):
        findings = sync_lint(
            tmp_path,
            """
            import threading
            import time

            _L = threading.Lock()


            def f():
                with _L:
                    pass
                time.sleep(0.1)
            """,
            ["HS003"],
        )
        assert findings == []

    def test_condition_wait_on_the_held_lock_is_clean(self, tmp_path):
        # Condition.wait RELEASES the condition it waits on — the one
        # blocking-while-held pattern that is the whole point of a CV
        findings = sync_lint(
            tmp_path,
            """
            import threading


            class W:
                def __init__(self):
                    self._cv = threading.Condition()

                def wait_ready(self):
                    with self._cv:
                        self._cv.wait()
            """,
            ["HS003"],
        )
        assert findings == []

    def test_wait_with_a_second_lock_held_is_flagged(self, tmp_path):
        findings = sync_lint(
            tmp_path,
            """
            import threading


            class W:
                def __init__(self):
                    self._cv = threading.Condition()
                    self._other = threading.Lock()

                def bad(self):
                    with self._other:
                        with self._cv:
                            self._cv.wait()
            """,
            ["HS003"],
        )
        assert [f.rule for f in findings] == ["HS003"]

    def test_flags_future_resolution_under_lock(self, tmp_path):
        # set_exception runs done-callbacks synchronously — resolving
        # futures under the queue lock is the batcher bug this PR fixed
        findings = sync_lint(
            tmp_path,
            """
            import threading


            class Q:
                def __init__(self):
                    self._lock = threading.Lock()

                def fail_all(self, futs, exc):
                    with self._lock:
                        for f in futs:
                            f.set_exception(exc)
            """,
            ["HS003"],
        )
        assert [f.rule for f in findings] == ["HS003"]
        assert "done-callbacks" in findings[0].message


# ---------------------------------------------------------------- HS004


class TestSpawnPolicy:
    def test_flags_spawn_without_policy(self, tmp_path):
        findings = sync_lint(
            tmp_path,
            """
            import threading


            def work():
                pass


            def main():
                t = threading.Thread(target=work)
                t.start()
            """,
            ["HS004"],
        )
        assert [f.rule for f in findings] == ["HS004"]

    def test_daemon_spawn_is_clean(self, tmp_path):
        findings = sync_lint(
            tmp_path,
            """
            import threading


            def work():
                pass


            def main():
                t = threading.Thread(target=work, daemon=True)
                t.start()
            """,
            ["HS004"],
        )
        assert findings == []

    def test_joined_spawn_is_clean(self, tmp_path):
        findings = sync_lint(
            tmp_path,
            """
            import threading


            def work():
                pass


            def main():
                t = threading.Thread(target=work)
                t.start()
                t.join()
            """,
            ["HS004"],
        )
        assert findings == []

    def test_cancelled_timer_is_clean(self, tmp_path):
        findings = sync_lint(
            tmp_path,
            """
            import threading


            def work():
                pass


            def main():
                t = threading.Timer(5.0, work)
                t.start()
                t.cancel()
            """,
            ["HS004"],
        )
        assert findings == []

    def test_local_timer_class_is_not_a_spawn(self, tmp_path):
        # the repo's utils.time_utils.Timer is a stopwatch; spawn
        # detection is import-aware and must not flag it
        findings = sync_lint(
            tmp_path,
            """
            class Timer:
                def __init__(self, name):
                    self.name = name


            def main():
                t = Timer("total_training")
                return t
            """,
            ["HS004", "HS005"],
        )
        assert findings == []

    def test_threading_import_alias_is_a_spawn(self, tmp_path):
        findings = sync_lint(
            tmp_path,
            """
            import threading as th


            def work():
                pass


            def main():
                t = th.Thread(target=work)
                t.start()
            """,
            ["HS004"],
        )
        assert [f.rule for f in findings] == ["HS004"]

    def test_from_import_timer_is_a_spawn(self, tmp_path):
        findings = sync_lint(
            tmp_path,
            """
            from threading import Timer


            def work():
                pass


            def main():
                Timer(5.0, work).start()
            """,
            ["HS004"],
        )
        assert [f.rule for f in findings] == ["HS004"]


# ---------------------------------------------------------------- HS005


class TestUndeclaredThreadRoot:
    def test_flags_unannotated_target(self, tmp_path):
        findings = sync_lint(
            tmp_path,
            """
            import threading


            def work():
                pass


            def main():
                threading.Thread(target=work, daemon=True).start()
            """,
            ["HS005"],
        )
        assert [f.rule for f in findings] == ["HS005"]
        assert "thread-root" in findings[0].message

    def test_annotated_target_is_clean(self, tmp_path):
        findings = sync_lint(
            tmp_path,
            """
            import threading


            # graftsync: thread-root
            def work():
                pass


            def main():
                threading.Thread(target=work, daemon=True).start()
            """,
            ["HS005"],
        )
        assert findings == []

    def test_lambda_target_is_flagged(self, tmp_path):
        findings = sync_lint(
            tmp_path,
            """
            import threading


            def main():
                threading.Thread(target=lambda: None, daemon=True).start()
            """,
            ["HS005"],
        )
        assert [f.rule for f in findings] == ["HS005"]
        assert "lambda" in findings[0].message

    def test_dynamic_target_stays_quiet(self, tmp_path):
        # an unresolvable callable: guessing would be noise
        findings = sync_lint(
            tmp_path,
            """
            import threading


            class S:
                def __init__(self, target):
                    self._target = target

                def start(self):
                    threading.Thread(
                        target=self._target, daemon=True
                    ).start()
            """,
            ["HS005"],
        )
        assert findings == []

    def test_annotated_method_target_is_clean(self, tmp_path):
        findings = sync_lint(
            tmp_path,
            """
            import threading


            class S:
                # graftsync: thread-root
                def _run(self):
                    pass

                def start(self):
                    threading.Thread(target=self._run, daemon=True).start()
            """,
            ["HS005"],
        )
        assert findings == []


# ---------------------------------------------------------------- HS006


CYCLE_SRC = """
import threading


class A:
    def __init__(self):
        self._la = threading.Lock()
        self._lb = threading.Lock()

    def ab(self):
        with self._la:
            with self._lb:
                pass

    def ba(self):
        with self._lb:
            with self._la:
                pass
"""

DAG_SRC = """
import threading


class A:
    def __init__(self):
        self._la = threading.Lock()
        self._lb = threading.Lock()

    def ab(self):
        with self._la:
            with self._lb:
                pass

    def also_ab(self):
        with self._la:
            with self._lb:
                pass
"""


class TestPotentialDeadlock:
    def test_flags_lock_order_cycle(self, tmp_path):
        findings = sync_lint(
            tmp_path, CYCLE_SRC, ["HS006"], full_tree=True
        )
        assert [f.rule for f in findings] == ["HS006"]
        assert "cycle" in findings[0].message
        assert "fixture.A._la" in findings[0].message

    def test_consistent_order_is_a_dag(self, tmp_path):
        findings = sync_lint(tmp_path, DAG_SRC, ["HS006"], full_tree=True)
        assert findings == []

    def test_cycle_through_a_held_call_is_found(self, tmp_path):
        # m1 holds la and calls m2 (which acquires lb); m3 holds lb and
        # calls m4 (which acquires la): la->lb->la without any
        # syntactically nested acquire
        findings = sync_lint(
            tmp_path,
            """
            import threading


            class A:
                def __init__(self):
                    self._la = threading.Lock()
                    self._lb = threading.Lock()

                def m2(self):
                    with self._lb:
                        pass

                def m1(self):
                    with self._la:
                        self.m2()

                def m4(self):
                    with self._la:
                        pass

                def m3(self):
                    with self._lb:
                        self.m4()
            """,
            ["HS006"],
            full_tree=True,
        )
        assert [f.rule for f in findings] == ["HS006"]

    def test_order_graph_export(self, tmp_path):
        p = tmp_path / "graph_fixture.py"
        p.write_text(textwrap.dedent(DAG_SRC))
        graph = CONC.build_lock_order(REPO_ROOT, paths=[str(p)])
        assert "graph_fixture.A._la" in graph["locks"]
        assert any(
            e["from"] == "graph_fixture.A._la"
            and e["to"] == "graph_fixture.A._lb"
            for e in graph["edges"]
        )

    def test_repo_lock_order_graph_is_a_dag(self):
        # the property the runtime witness asserts against: the shipped
        # tree's static lock-order graph must be cycle-free
        rules = CONC.concurrency_rules(REPO_ROOT)
        hs006 = [r for r in rules if r.id == "HS006"]
        findings = CORE.run_lint(
            REPO_ROOT, hs006, baseline=None, full_tree=True
        )
        assert findings == [], "\n".join(f.render() for f in findings)


# -------------------------------------------------- annotation grammar


class TestAnnotationGrammar:
    def test_lock_annotation_names_the_lock(self, tmp_path):
        p = tmp_path / "named.py"
        p.write_text(
            textwrap.dedent(
                """
                import threading

                _GL = threading.Lock()  # graftsync: lock=custom.global_lock


                class C:
                    def __init__(self):
                        self._l = threading.Lock()  # graftsync: lock=custom.inner

                    def both(self):
                        with _GL:
                            with self._l:
                                pass
                """
            )
        )
        graph = CONC.build_lock_order(REPO_ROOT, paths=[str(p)])
        assert "custom.global_lock" in graph["locks"]
        assert any(
            e["from"] == "custom.global_lock" and e["to"] == "custom.inner"
            for e in graph["edges"]
        )

    def test_maybe_wrap_name_arg_names_the_lock(self, tmp_path):
        p = tmp_path / "wrapped.py"
        p.write_text(
            textwrap.dedent(
                """
                import threading

                from hydragnn_tpu.utils import syncdebug


                class C:
                    def __init__(self):
                        self._a = syncdebug.maybe_wrap(
                            threading.Lock(), "wrapped.A"
                        )
                        self._b = syncdebug.maybe_wrap(
                            threading.Lock(), "wrapped.B"
                        )

                    def nested(self):
                        with self._a:
                            with self._b:
                                pass
                """
            )
        )
        graph = CONC.build_lock_order(REPO_ROOT, paths=[str(p)])
        assert "wrapped.A" in graph["locks"]
        assert any(
            e["from"] == "wrapped.A" and e["to"] == "wrapped.B"
            for e in graph["edges"]
        )


# ------------------------------------------------------- suppressions


class TestSuppressions:
    def test_same_line_suppression(self, tmp_path):
        # the module-global finding anchors on the ``global`` statement
        findings = sync_lint(
            tmp_path,
            """
            _COUNT = 0


            def bump():
                global _COUNT  # graftsync: disable=HS001 -- test fixture
                _COUNT += 1
            """,
            ["HS001"],
        )
        assert findings == []

    def test_line_above_suppression(self, tmp_path):
        findings = sync_lint(
            tmp_path,
            """
            _COUNT = 0


            def bump():
                # graftsync: disable=HS001 -- test fixture
                global _COUNT
                _COUNT += 1
            """,
            ["HS001"],
        )
        assert findings == []

    def test_wrong_rule_suppression_does_not_mask(self, tmp_path):
        findings = sync_lint(
            tmp_path,
            """
            _COUNT = 0


            def bump():
                global _COUNT  # graftsync: disable=HS003 -- wrong rule
                _COUNT += 1
            """,
            ["HS001"],
        )
        assert [f.rule for f in findings] == ["HS001"]


# ------------------------------------------------------------ baseline


class TestBaseline:
    SRC = (
        "import threading\n\n_L = threading.Lock()\n\n\n"
        "def f(work):\n    _L.acquire()\n    work()\n    _L.release()\n"
    )

    def test_round_trip_silences_grandfathered_findings(self, tmp_path):
        fixture = tmp_path / "legacy.py"
        fixture.write_text(self.SRC)

        def rules():
            return [
                r for r in CONC.concurrency_rules(REPO_ROOT)
                if r.id == "HS002"
            ]

        findings = CORE.run_lint(REPO_ROOT, rules(), paths=[str(fixture)])
        assert len(findings) == 1

        baseline = tmp_path / "baseline.json"
        CORE.write_baseline(str(baseline), findings, tool="graftsync")
        again = CORE.run_lint(
            REPO_ROOT, rules(), paths=[str(fixture)],
            baseline=str(baseline),
        )
        assert again == []

    def test_fingerprint_survives_line_churn(self, tmp_path):
        fixture = tmp_path / "churn.py"
        fixture.write_text(self.SRC)

        def rules():
            return [
                r for r in CONC.concurrency_rules(REPO_ROOT)
                if r.id == "HS002"
            ]

        (f1,) = CORE.run_lint(REPO_ROOT, rules(), paths=[str(fixture)])
        fixture.write_text("import os\n\n\n" + self.SRC)
        (f2,) = CORE.run_lint(REPO_ROOT, rules(), paths=[str(fixture)])
        assert f1.line != f2.line
        assert f1.fingerprint() == f2.fingerprint()

    def test_committed_baseline_is_empty(self):
        with open(BASELINE) as f:
            data = json.load(f)
        assert data["findings"] == []
        assert "graftsync" in data["comment"]


# ----------------------------------------------------------------- CLI


RULE_FIXTURES = {
    "HS001": (
        "import threading\n\n\nclass Box:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self._items = []\n\n"
        "    def add(self, x):\n"
        "        self._items.append(x)\n"
    ),
    "HS002": TestBaseline.SRC,
    "HS003": (
        "import threading\nimport time\n\n_L = threading.Lock()\n\n\n"
        "def f():\n    with _L:\n        time.sleep(0.1)\n"
    ),
    "HS004": (
        "import threading\n\n\ndef work():\n    pass\n\n\n"
        "def main():\n    t = threading.Thread(target=work)\n"
        "    t.start()\n"
    ),
    "HS005": (
        "import threading\n\n\ndef work():\n    pass\n\n\n"
        "def main():\n"
        "    threading.Thread(target=work, daemon=True).start()\n"
    ),
    "HS006": textwrap.dedent(CYCLE_SRC),
}


class TestCli:
    @pytest.mark.parametrize("rule_id", sorted(RULE_FIXTURES))
    def test_each_rule_individually_rejects_its_fixture(
        self, tmp_path, rule_id
    ):
        # the ci.sh self-test contract: one injected violation per HS
        # rule, each must fail the gate on its own
        fixture = tmp_path / f"{rule_id.lower()}_fixture.py"
        fixture.write_text(RULE_FIXTURES[rule_id])
        rc = CLI.main(
            [str(fixture), "--rule", rule_id, "--strict", "--no-baseline"]
        )
        assert rc == 1, f"{rule_id} did not reject its fixture"

    def test_json_artifact(self, tmp_path):
        fixture = tmp_path / "bad.py"
        fixture.write_text(RULE_FIXTURES["HS002"])
        out = tmp_path / "findings.json"
        rc = CLI.main(
            [str(fixture), "--rule", "HS002", "--strict", "--no-baseline",
             "--json", str(out)]
        )
        assert rc == 1
        payload = json.loads(out.read_text())
        assert payload["count"] == 1
        assert payload["findings"][0]["rule"] == "HS002"

    def test_order_graph_export(self, tmp_path):
        out = tmp_path / "graph.json"
        rc = CLI.main(["--order-graph", str(out)])
        assert rc == 0
        graph = json.loads(out.read_text())
        assert set(graph) == {"locks", "edges"}

    def test_unknown_rule_is_usage_error(self):
        assert CLI.main(["--rule", "HS999"]) == 2

    def test_list_rules(self, capsys):
        assert CLI.main(["--list-rules"]) == 0
        listed = capsys.readouterr().out
        for rid in ("HS001", "HS006"):
            assert rid in listed


# ----------------------------------------------------- runtime witness


from hydragnn_tpu.utils import syncdebug  # noqa: E402


@pytest.fixture
def witness(monkeypatch):
    """Enable the witness with a clean slate; static seeding is skipped
    (it scans the whole tree) except where a test re-arms it."""
    monkeypatch.setenv("HYDRAGNN_LOCK_DEBUG", "1")
    monkeypatch.delenv("HYDRAGNN_INJECT_LOCK_ORDER", raising=False)
    syncdebug.reset()
    syncdebug._STATIC_SEEDED = True
    yield syncdebug
    syncdebug.reset()


class TestRuntimeWitness:
    def test_off_by_default_returns_the_raw_lock(self, monkeypatch):
        monkeypatch.delenv("HYDRAGNN_LOCK_DEBUG", raising=False)
        syncdebug.reset()
        try:
            lock = threading.Lock()
            assert syncdebug.maybe_wrap(lock, "off.raw") is lock
        finally:
            syncdebug.reset()

    def test_consistent_order_records_no_violation(self, witness):
        a = witness.maybe_wrap(threading.Lock(), "w1.A")
        b = witness.maybe_wrap(threading.Lock(), "w1.B")
        for _ in range(3):
            with a:
                with b:
                    pass
        assert witness.violations() == []

    def test_inversion_fires_once_per_edge(self, witness):
        a = witness.maybe_wrap(threading.Lock(), "w2.A")
        b = witness.maybe_wrap(threading.Lock(), "w2.B")
        with a:
            with b:
                pass
        for _ in range(2):  # the edge dedupes: one violation, not two
            with b:
                with a:
                    pass
        v = witness.violations()
        assert len(v) == 1
        assert v[0]["locks"] == ["w2.B", "w2.A"]  # [held, acquiring]
        assert v[0]["conflict"] == "w2.A->w2.B"
        assert v[0]["stacks"]  # every thread's stack is attached
        assert v[0]["injected"] is False

    def test_transitive_inversion_is_caught(self, witness):
        a = witness.maybe_wrap(threading.Lock(), "w3.A")
        b = witness.maybe_wrap(threading.Lock(), "w3.B")
        c = witness.maybe_wrap(threading.Lock(), "w3.C")
        with a:
            with b:
                pass
        with b:
            with c:
                pass
        with c:  # A->B->C is on record; C->A closes the cycle
            with a:
                pass
        v = witness.violations()
        assert len(v) == 1 and v[0]["locks"] == ["w3.C", "w3.A"]

    def test_acquire_release_protocol_and_wait(self, witness):
        cv = witness.maybe_wrap(threading.Condition(), "w4.cv")
        other = witness.maybe_wrap(threading.Lock(), "w4.other")
        assert cv.acquire()
        cv.wait(timeout=0.01)  # releases + re-notes; must not corrupt
        cv.release()
        got = other.acquire(timeout=1)
        assert got
        other.release()
        assert witness.violations() == []

    def test_violation_lands_in_flight_record(self, witness, tmp_path):
        from hydragnn_tpu.obs import flight as flight_mod

        path = str(tmp_path / "flight.jsonl")
        fr = flight_mod.FlightRecorder(path, enabled=True)
        a = witness.maybe_wrap(threading.Lock(), "w5.A")
        b = witness.maybe_wrap(threading.Lock(), "w5.B")
        with a:
            with b:
                pass
        with b:
            with a:
                pass
        fr.close()
        events = [json.loads(l) for l in open(path)]
        (ev,) = [e for e in events if e["kind"] == "lock_order"]
        assert ev["locks"] == ["w5.B", "w5.A"]
        assert ev["stacks"]
        # the event validates against the flight schema
        assert flight_mod.validate_flight_record(events) == []

    def test_witness_survives_a_raising_flight_recorder(self, witness):
        class Exploding:
            def record(self, kind, **payload):
                raise RuntimeError("flight write failed")

        exploding = Exploding()
        witness.register_flight(exploding)
        a = witness.maybe_wrap(threading.Lock(), "w6.A")
        b = witness.maybe_wrap(threading.Lock(), "w6.B")
        with a:
            with b:
                pass
        with b:
            with a:  # must not raise despite the recorder exploding
                pass
        assert len(witness.violations()) == 1

    def test_injection_is_one_shot(self, witness, monkeypatch):
        monkeypatch.setenv("HYDRAGNN_INJECT_LOCK_ORDER", "w7.A,w7.B")
        witness.maybe_wrap(threading.Lock(), "w7.A")
        witness.maybe_wrap(threading.Lock(), "w7.B")
        v = witness.violations()
        assert len(v) == 1 and v[0]["injected"] is True
        assert v[0]["locks"] == ["w7.B", "w7.A"]
        # registering more locks does not re-fire the injection
        witness.maybe_wrap(threading.Lock(), "w7.C")
        assert len(witness.violations()) == 1

    def test_static_seed_loads_the_graftsync_graph(self, monkeypatch):
        monkeypatch.setenv("HYDRAGNN_LOCK_DEBUG", "1")
        monkeypatch.delenv("HYDRAGNN_INJECT_LOCK_ORDER", raising=False)
        syncdebug.reset()
        try:
            syncdebug.maybe_wrap(threading.Lock(), "seed.trigger")
            static = CONC.build_lock_order(REPO_ROOT)
            edges = {
                (e["from"], e["to"]) for e in static["edges"]
            }
            with syncdebug._STATE_LOCK:
                seen = set(syncdebug._SEEN_EDGES)
            assert edges <= seen
        finally:
            syncdebug.reset()

    def test_contradicting_a_static_edge_fires(self, monkeypatch):
        monkeypatch.setenv("HYDRAGNN_LOCK_DEBUG", "1")
        monkeypatch.delenv("HYDRAGNN_INJECT_LOCK_ORDER", raising=False)
        syncdebug.reset()
        try:
            syncdebug.maybe_wrap(threading.Lock(), "seed.trigger2")
            static = CONC.build_lock_order(REPO_ROOT)
            if not static["edges"]:
                pytest.skip("tree has no static lock-order edges")
            edge = static["edges"][0]
            a = syncdebug.maybe_wrap(threading.Lock(), edge["from"])
            b = syncdebug.maybe_wrap(threading.Lock(), edge["to"])
            with b:  # contradicts the STATIC order without any runtime
                with a:  # observation of the forward direction
                    pass
            v = syncdebug.violations()
            assert len(v) == 1
            assert v[0]["locks"] == [edge["to"], edge["from"]]
        finally:
            syncdebug.reset()


# -------------------------------------- concurrency-fix regressions


class TestConcurrencyRegressions:
    def test_cancel_pending_survives_reentrant_done_callback(self):
        # resolving a future runs its done-callbacks synchronously; a
        # callback that touches the queue used to deadlock on the
        # non-reentrant Condition (futures were resolved under _cv)
        from hydragnn_tpu.serve.batcher import MicroBatchQueue

        q = MicroBatchQueue(
            num_buckets=1, max_batch=8, max_delay_s=0.5, max_pending=16
        )
        depths = []
        fut = q.put(0, "item")
        fut.add_done_callback(lambda f: depths.append(q.depth()))

        boom = RuntimeError("teardown")
        result = {}

        def cancel():
            result["n"] = q.cancel_pending(boom)

        t = threading.Thread(target=cancel, daemon=True)
        t.start()
        t.join(timeout=5)
        assert not t.is_alive(), "cancel_pending deadlocked on re-entry"
        assert result["n"] == 1
        assert fut.exception() is boom
        assert depths == [0]  # the callback really re-entered the queue

    def test_flight_record_racing_close_mid_serialization(self, tmp_path):
        # _jsonable calls payload.tolist() BEFORE taking the recorder
        # lock; a tolist that closes the recorder used to leave record()
        # writing to a closed file
        from hydragnn_tpu.obs.flight import FlightRecorder

        path = str(tmp_path / "f.jsonl")
        fr = FlightRecorder(path, enabled=True)

        class ClosesDuringSerialization:
            def tolist(self):
                fr.close()
                return [1, 2]

        fr.record("error", error="x", error_type="E",
                  data=ClosesDuringSerialization())  # must not raise
        for line in open(path):
            json.loads(line)  # no partial line ever hit the file

    def test_profile_capture_slot_stays_busy_through_stop(
        self, tmp_path, monkeypatch
    ):
        # stop_trace blocks (device sync); the slot must read busy until
        # it returns or a concurrent try_start would start a trace this
        # teardown then kills
        from hydragnn_tpu.utils import profile

        entered = threading.Event()
        release = threading.Event()
        monkeypatch.setattr(
            profile.jax.profiler, "start_trace", lambda prefix: None
        )

        def slow_stop():
            entered.set()
            assert release.wait(5)

        monkeypatch.setattr(profile.jax.profiler, "stop_trace", slow_stop)

        assert profile.try_start_capture(str(tmp_path / "p1"))
        assert profile.capture_active()
        t = threading.Thread(target=profile.stop_capture, daemon=True)
        t.start()
        assert entered.wait(5)
        assert profile.capture_active()  # "stopping" still occupies it
        assert not profile.try_start_capture(str(tmp_path / "p2"))
        release.set()
        t.join(timeout=5)
        assert not t.is_alive()
        assert not profile.capture_active()
        # the slot is reusable after a full stop
        assert profile.try_start_capture(str(tmp_path / "p3"))
        profile.stop_capture()
        assert not profile.capture_active()

    def test_registry_rank_resolves_outside_the_lock(self, monkeypatch):
        # jax.process_index can block on backend init for seconds; the
        # probe acquires the registry lock from inside it — held-lock
        # resolution would deadlock (caught by the timeout)
        import hydragnn_tpu.obs.registry as obs_registry

        reg = obs_registry.MetricsRegistry()

        class _FakeJax:
            @staticmethod
            def process_index():
                got = reg._lock.acquire(timeout=2)
                assert got, "rank resolved while holding the registry lock"
                reg._lock.release()
                return 7

        import sys as _sys

        monkeypatch.setitem(_sys.modules, "jax", _FakeJax())
        assert reg.rank == 7
        assert reg.rank == 7  # cached; the fake is not re-entered

    def test_trace_to_dict_snapshots_spans(self):
        from hydragnn_tpu.obs.trace import RequestTrace

        tr = RequestTrace("deadbeefdeadbeef", seq=1, attrs={"k": "v"})
        tr.mark("route")
        d = tr.to_dict()
        assert d["spans"] is not tr.spans
        assert d["attrs"] is not tr.attrs
        before = len(d["spans"])
        tr.mark("late")  # a late mark must not mutate the export
        assert len(d["spans"]) == before

    def test_compile_monitor_registers_one_dispatcher(self, monkeypatch):
        import sys as _sys

        import hydragnn_tpu.obs.compile_monitor as cmon

        registrations = []

        class _FakeMonitoring:
            @staticmethod
            def register_event_duration_secs_listener(fn):
                registrations.append(fn)

        # `import jax.monitoring as mon` binds via getattr(jax, ...)
        # when jax is already loaded, so patch both lookup paths
        import jax as _jax

        fake = _FakeMonitoring()
        monkeypatch.setitem(_sys.modules, "jax.monitoring", fake)
        monkeypatch.setattr(_jax, "monitoring", fake, raising=False)
        monkeypatch.setattr(cmon, "_dispatcher_registered", False)

        barrier = threading.Barrier(4)
        monitors = [cmon.CompileMonitor() for _ in range(4)]

        def start(m):
            barrier.wait(timeout=5)
            m.start()

        threads = [
            threading.Thread(target=start, args=(m,), daemon=True)
            for m in monitors
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=5)
        assert len(registrations) == 1, (
            "concurrent starts double-registered the dispatcher: every "
            "compile would be counted twice forever"
        )
        for m in monitors:
            assert m.available
            m.stop()

    def test_diststore_close_is_lock_disciplined(self):
        # close() drains the connection map under the lock and closes
        # the sockets outside it — a concurrent fetch either keeps its
        # conn (and gets ConnectionError) or re-caches a fresh one
        import socket as socket_mod

        from hydragnn_tpu.data.diststore import DistSampleStore

        store = DistSampleStore.__new__(DistSampleStore)
        store._lock = threading.Lock()
        s1, s2 = socket_mod.socketpair()
        store._conns = {1: s1}
        store._server = None
        store.close()
        assert store._conns == {}
        assert s1.fileno() == -1  # really closed
        s2.close()


# ------------------------------------------------------------ meta-test


class TestShippedTree:
    def test_tree_is_graftsync_clean_with_committed_baseline(self):
        findings = CORE.run_lint(
            REPO_ROOT,
            CONC.concurrency_rules(REPO_ROOT),
            baseline=BASELINE,
            full_tree=True,
        )
        assert findings == [], "\n" + "\n".join(
            f.render() for f in findings
        )
