"""Multi-device data-parallel tests on the 8-device virtual CPU mesh.

The reference tests distributed behavior with ``mpirun -n 2`` in CI
(reference: .github/workflows/CI.yml); the TPU-native analog exercises the
sharded train/eval path over an 8-device mesh (conftest.py forces
``--xla_force_host_platform_device_count=8``).
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from hydragnn_tpu.data.synthetic import deterministic_graph_data
from hydragnn_tpu.data.ingest import prepare_dataset
from hydragnn_tpu.data.loader import GraphLoader
from hydragnn_tpu.models.create import create_model_config
from hydragnn_tpu.parallel import (
    make_mesh,
    make_sharded_eval_step,
    make_sharded_train_step,
    place_state,
)
from hydragnn_tpu.train import create_train_state, make_train_step, select_optimizer
from hydragnn_tpu.train.loop import test_epoch as run_test_epoch
from hydragnn_tpu.utils.config import update_config

from test_data_pipeline import base_config

D = 8  # virtual devices from conftest


@pytest.fixture(scope="module")
def dp_problem():
    cfg = base_config(multihead=True)
    cfg["NeuralNetwork"]["Architecture"]["model_type"] = "GIN"
    cfg["NeuralNetwork"]["Training"]["batch_size"] = 16
    samples = deterministic_graph_data(number_configurations=96, seed=5)
    train, val, test, _, _ = prepare_dataset(samples, cfg)
    cfg = update_config(cfg, train, val, test)
    loader = GraphLoader(train, 16, shuffle=True, device_stack=D, drop_last=True)
    example_stacked = next(iter(loader))
    example = jax.tree_util.tree_map(lambda x: x[0], example_stacked)
    model, variables = create_model_config(cfg["NeuralNetwork"], example)
    return cfg, model, variables, loader


def pytest_sharded_train_step_runs_and_learns(dp_problem):
    cfg, model, variables, loader = dp_problem
    mesh = make_mesh(D)
    tx = select_optimizer({"Optimizer": {"type": "AdamW", "learning_rate": 0.01}})
    state = place_state(mesh, create_train_state(variables, tx))
    step = make_sharded_train_step(model, tx, mesh)

    losses = []
    for epoch in range(10):
        loader.set_epoch(epoch)
        for batch in loader:
            state, loss, tasks = step(state, batch)
        losses.append(float(loss))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0] * 0.9, f"no learning: {losses}"


def pytest_sharded_matches_single_device(dp_problem):
    """With equal-sized sub-batches, pmean-of-per-device-grads equals the
    single-device step on the concatenated batch to float tolerance."""
    cfg, model, variables, loader = dp_problem
    mesh = make_mesh(D)
    tx = select_optimizer({"Optimizer": {"type": "SGD", "learning_rate": 0.05}})

    stacked = next(iter(loader))

    # single-device: average the 8 sub-batch grads by hand via vmapped steps
    single_step = make_train_step(model, tx)
    sub_states = []
    for d in range(D):
        sub = jax.tree_util.tree_map(lambda x: np.asarray(x)[d], stacked)
        st = create_train_state(variables, tx)
        st2, loss, _ = single_step(st, sub)
        sub_states.append(jax.device_get(st2.params))
    # SGD: param' = param - lr*grad  =>  mean over devices of param'
    # equals param - lr*pmean(grad) when sub-batches weight equally.
    manual = jax.tree_util.tree_map(
        lambda *xs: np.mean(np.stack(xs), axis=0), *sub_states
    )

    state = place_state(mesh, create_train_state(variables, tx))
    sharded_step = make_sharded_train_step(model, tx, mesh)
    new_state, loss, tasks = sharded_step(state, stacked)
    sharded = jax.device_get(new_state.params)

    flat_a = jax.tree_util.tree_leaves(manual)
    flat_b = jax.tree_util.tree_leaves(sharded)
    for a, b in zip(flat_a, flat_b):
        np.testing.assert_allclose(a, b, rtol=2e-4, atol=2e-5)


def pytest_zero1_opt_state_is_sharded(dp_problem):
    cfg, model, variables, loader = dp_problem
    mesh = make_mesh(D)
    tx = select_optimizer({"Optimizer": {"type": "AdamW", "learning_rate": 0.01}})
    state = place_state(mesh, create_train_state(variables, tx), zero1=True)

    # at least one optimizer-state leaf must actually be sharded over 'data'
    sharded_leaves = [
        x
        for x in jax.tree_util.tree_leaves(state.opt_state)
        if hasattr(x, "sharding") and x.sharding.spec == jax.sharding.PartitionSpec("data")
    ]
    assert sharded_leaves, "no ZeRO-1 sharded optimizer leaves"

    step = make_sharded_train_step(model, tx, mesh, zero1=True)
    stacked = next(iter(loader))
    state, loss, _ = step(state, stacked)
    assert np.isfinite(float(loss))

    # and the result must match the replicated layout run
    state_rep = place_state(mesh, create_train_state(variables, tx))
    step_rep = make_sharded_train_step(model, tx, mesh)
    state_rep, loss_rep, _ = step_rep(state_rep, stacked)
    np.testing.assert_allclose(float(loss), float(loss_rep), rtol=1e-5)
    for a, b in zip(
        jax.tree_util.tree_leaves(jax.device_get(state.params)),
        jax.tree_util.tree_leaves(jax.device_get(state_rep.params)),
    ):
        np.testing.assert_allclose(a, b, rtol=2e-4, atol=2e-5)


def pytest_sharded_eval_with_outputs(dp_problem):
    cfg, model, variables, loader = dp_problem
    mesh = make_mesh(D)
    tx = select_optimizer({"Optimizer": {"type": "AdamW", "learning_rate": 0.01}})
    state = place_state(mesh, create_train_state(variables, tx))
    ev = make_sharded_eval_step(model, mesh, with_outputs=True)
    loss, tasks, trues, preds = run_test_epoch(
        loader, state, ev, model.cfg, return_samples=True
    )
    assert np.isfinite(loss)
    # collected values must cover exactly the real (unpadded) graphs
    assert trues[0].shape == preds[0].shape
    assert trues[0].shape[0] == len(loader) * 16  # drop_last: full batches only
    # node head values cover real nodes
    assert trues[1].shape == preds[1].shape
    assert trues[1].shape[0] > trues[0].shape[0]

def pytest_sharded_remat_matches_plain(dp_problem):
    """remat=True on the sharded step is numerically a no-op."""
    cfg, model, variables, loader = dp_problem
    mesh = make_mesh(D)
    tx = select_optimizer({"Optimizer": {"type": "AdamW", "learning_rate": 0.01}})
    stacked = next(iter(loader))

    results = []
    for remat in (False, True):
        state = place_state(mesh, create_train_state(variables, tx))
        step = make_sharded_train_step(model, tx, mesh, remat=remat)
        state, loss, _ = step(state, stacked)
        results.append((float(loss), jax.device_get(state.params)))
    assert np.isfinite(results[0][0])
    np.testing.assert_allclose(results[0][0], results[1][0], rtol=1e-6)
    for a, b in zip(
        jax.tree_util.tree_leaves(results[0][1]),
        jax.tree_util.tree_leaves(results[1][1]),
    ):
        np.testing.assert_allclose(a, b, rtol=1e-6, atol=1e-7)


def pytest_scaling_harness_loss_parity(monkeypatch):
    """bench_scaling's harness on the virtual 8-device mesh: every mesh
    width's first-step loss equals the 1-device run (DDP equivalence),
    and the artifact has the full per-size schema."""
    import bench_scaling

    monkeypatch.setenv("BENCH_SMOKE", "1")
    rec = bench_scaling.run(sizes=[1, 2, 4, 8])
    assert rec["virtual_cpu_mesh"] is True
    for d in ("1", "2", "4", "8"):
        size = rec["sizes"][d]
        assert size["loss_matches_serial"], (d, size)
        assert size["graphs_per_sec"] > 0
        # efficiency figures are only published on real hardware — a
        # virtual CPU mesh's would be meaningless (shared host cores)
        assert "parallel_efficiency" not in size
