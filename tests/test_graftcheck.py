"""graftcheck (hydragnn_tpu/lint/ir.py): per-contract true-positive /
near-miss fixtures over the pure text walkers, deterministic tiny-jax
lowering fixtures, the injection spec, baseline round-trip, the in-run
``contract_block``, and the (slow) meta-test that the shipped tree
passes all six contracts under both CI layouts.

The text-walker fixtures are golden StableHLO/HLO snippets shaped like
what jax 0.4.x emits — the walkers are pure string functions, so the
fixtures pin the exact textual forms each contract keys on (and the
near-misses pin what must NOT trigger it).
"""

import json
import os

import jax
import jax.numpy as jnp
import pytest

from hydragnn_tpu.lint import ir
from hydragnn_tpu.lint.core import load_baseline, write_baseline

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ------------------------------------------------------- CC001 walkers


class TestHostTransferScan:
    def test_flags_host_callback_custom_call(self):
        text = (
            'stablehlo.custom_call @xla_python_cpu_callback(%arg0) '
            '{api_version = 2 : i32} : (tensor<f32>) -> tensor<f32>'
        )
        assert ir.scan_host_transfers(text) == ["xla_python_cpu_callback"]

    def test_flags_infeed(self):
        assert ir.scan_host_transfers(
            '"stablehlo.infeed"(%tok) : (!stablehlo.token) -> tensor<8xf32>'
        ) == ["stablehlo.infeed"]

    def test_clean_module_is_clean(self):
        # a custom_call that is NOT a host callback (Sharding, pallas)
        # must not trigger — the near-miss the r05 incident teaches
        text = (
            'stablehlo.custom_call @Sharding(%0) : (tensor<8xf32>) -> tensor<8xf32>\n'
            'stablehlo.custom_call @tpu_custom_call(%1) {backend_config = ""}'
        )
        assert ir.scan_host_transfers(text) == []

    def test_real_pure_callback_lowering_is_caught(self):
        # deterministic tiny lowering: jax.pure_callback must land one
        # of the registered marker strings in the StableHLO text
        def f(x):
            return jax.pure_callback(
                lambda v: v, jax.ShapeDtypeStruct((), jnp.float32), x
            )

        text = jax.jit(f).lower(jnp.float32(1.0)).as_text()
        assert ir.scan_host_transfers(text)

    def test_real_clean_lowering_is_clean(self):
        text = jax.jit(lambda x: x * 2).lower(jnp.float32(1.0)).as_text()
        assert ir.scan_host_transfers(text) == []


# ------------------------------------------------------- CC002 walkers


class TestEdgeDtypeScan:
    EDGE_PAD = 120

    def test_flags_all_f32_edge_dot(self):
        text = (
            "%3 = stablehlo.dot_general %1, %2, contracting_dims = [1] x [0] "
            ": (tensor<120x16xf32>, tensor<16x32xf32>) -> tensor<120x32xf32>"
        )
        bad = ir.scan_edge_f32_dots(text, self.EDGE_PAD)
        assert len(bad) == 1 and "120x16" in bad[0]

    def test_bf16_edge_dot_is_clean(self):
        # the contract: STREAMED operands bf16; f32 accumulation fine
        text = (
            "%3 = stablehlo.dot_general %1, %2, contracting_dims = [1] x [0] "
            ": (tensor<120x16xbf16>, tensor<16x32xbf16>) -> tensor<120x32xf32>"
        )
        assert ir.scan_edge_f32_dots(text, self.EDGE_PAD) == []

    def test_node_level_f32_dot_is_clean(self):
        # near-miss: an f32 dot whose leading dim is the NODE pad —
        # head/node dots legitimately stay f32
        text = (
            "%3 = stablehlo.dot_general %1, %2, contracting_dims = [1] x [0] "
            ": (tensor<64x16xf32>, tensor<16x32xf32>) -> tensor<64x32xf32>"
        )
        assert ir.scan_edge_f32_dots(text, self.EDGE_PAD) == []

    def test_bf16_presence_counter(self):
        assert ir.count_bf16_values("tensor<8x4xbf16> tensor<8xbf16>") == 2
        assert ir.count_bf16_values("tensor<8x4xf32>") == 0


# ------------------------------------------------------- CC003 walkers


class TestCollectiveAudit:
    def test_parses_iota_form(self):
        text = (
            "  %ag = bf16[2,64] all-gather(%p), replica_groups=[4,2]<=[8], "
            "dimensions={0}"
        )
        (c,) = ir.parse_collectives(text)
        assert (c.kind, c.group_count, c.group_size) == ("all-gather", 4, 2)

    def test_parses_explicit_form(self):
        text = "  %ar = f32[] all-reduce(%l), replica_groups={{0,1,2,3,4,5,6,7}}"
        (c,) = ir.parse_collectives(text)
        assert (c.kind, c.group_count, c.group_size) == ("all-reduce", 1, 8)

    def test_flags_gather_in_pure_dp(self):
        colls = [ir.Collective("all-gather", 1, 8)]
        problems = ir.audit_collectives(colls, data=8, fsdp=1)
        assert problems and "pure-DP" in problems[0]

    def test_flags_permute_always(self):
        colls = [ir.Collective("collective-permute", None, None)]
        assert ir.audit_collectives(colls, data=8, fsdp=1)

    def test_flags_wrong_gather_group_size(self):
        colls = [ir.Collective("all-gather", 2, 4)]
        problems = ir.audit_collectives(colls, data=4, fsdp=2)
        assert problems and "refunds FSDP" in problems[0]

    def test_expected_fsdp_pattern_is_clean(self):
        # near-miss: exactly the layout-implied set — fsdp gathers of
        # size fsdp, batch-axis all-reduce, fsdp reduce-scatter
        colls = [
            ir.Collective("all-gather", 4, 2),
            ir.Collective("all-reduce", 1, 8),
            ir.Collective("all-reduce", 2, 4),
            ir.Collective("reduce-scatter", 4, 2),
        ]
        assert ir.audit_collectives(colls, data=4, fsdp=2) == []

    def test_zero1_reduce_scatter_is_clean(self):
        colls = [ir.Collective("reduce-scatter", 1, 8)]
        assert ir.audit_collectives(colls, data=8, fsdp=1, zero1=True) == []
        assert ir.audit_collectives(colls, data=8, fsdp=1, zero1=False)


# ------------------------------------------------------- CC004 walkers


class TestBucketStability:
    def test_flags_dynamic_dim(self):
        assert ir.scan_dynamic_dims("func @f(%a: tensor<?x128xf32>)")
        assert ir.scan_dynamic_dims("-> tensor<12x?xf32>")

    def test_static_dims_are_clean(self):
        assert not ir.scan_dynamic_dims("func @f(%a: tensor<12x128xf32>)")

    def _setup(self, signatures):
        return ir.CheckSetup(
            layout="global",
            data=1,
            fsdp=1,
            zero1=False,
            entries=[],
            bucket_signatures=signatures,
            residency_shapes=[],
        )

    def test_flags_signature_collision(self):
        sig = ((( 64, 8), "float32"),)
        findings = ir.check_setup(
            self._setup([("b0", sig), ("b1", sig)]), ["CC004"]
        )
        assert [f.rule for f in findings] == ["CC004"]
        assert "collides" in findings[0].message

    def test_distinct_signatures_are_clean(self):
        findings = ir.check_setup(
            self._setup(
                [("b0", (((64, 8), "f32"),)), ("b1", (((128, 8), "f32"),))]
            ),
            ["CC004"],
        )
        assert findings == []


# ------------------------------------------------------- CC005 walkers


class TestDonationScan:
    def test_flags_both_marker_spellings(self):
        assert ir.scan_donation_markers("%arg0 {tf.aliasing_output = 0 : i32}")
        assert ir.scan_donation_markers("%arg0 {jax.buffer_donor = true}")

    def test_unmarked_module_fails(self):
        assert not ir.scan_donation_markers(
            "func.func public @main(%arg0: tensor<8xf32>)"
        )

    def test_compiled_aliasing(self):
        assert ir.scan_compiled_aliasing(
            "HloModule jit_step, input_output_alias={ {0}: (0, {}, may-alias) }"
        )
        # near-miss: an EMPTY aliasing map means donation did not land
        assert not ir.scan_compiled_aliasing(
            "HloModule jit_step, input_output_alias={}"
        )

    def test_real_donated_lowering_carries_marker(self):
        step = jax.jit(lambda s, b: s + b, donate_argnums=(0,))
        text = step.lower(jnp.ones((4,)), jnp.ones((4,))).as_text()
        assert ir.scan_donation_markers(text)
        undonated = jax.jit(lambda s, b: s + b)
        assert not ir.scan_donation_markers(
            undonated.lower(jnp.ones((4,)), jnp.ones((4,))).as_text()
        )


# ------------------------------------------------------- CC006 budget


class TestVmemBudget:
    def test_flags_over_budget_shape(self):
        findings = ir.check_vmem_budget([(4096, 128)], budget_bytes=4096)
        assert findings and findings[0].rule == "CC006"
        assert "fall back" in findings[0].message

    def test_within_budget_is_clean(self):
        assert ir.check_vmem_budget([(64, 8)], budget_bytes=12 * 2**20) == []

    def test_flags_overpromised_budget(self):
        # a >16MB budget is a config lie even when every shape fits it
        findings = ir.check_vmem_budget([(64, 8)], budget_bytes=64 * 2**20)
        assert [f.rule for f in findings] == ["CC006"]
        assert "over-promises" in findings[0].message


# ----------------------------------------------------- injection knob


class TestInjectionSpec:
    def test_parse_valid_spec(self):
        assert ir.parse_inject_spec("cc001, CC004") == {"cc001", "cc004"}
        assert ir.parse_inject_spec(None) == set()
        assert ir.parse_inject_spec("") == set()

    def test_unknown_token_raises(self):
        with pytest.raises(ValueError, match="cc099"):
            ir.parse_inject_spec("cc001,cc099")

    def test_active_injections_reads_registered_knob(self, monkeypatch):
        # satellite coverage: the graftcheck injection knob is part of
        # the HYDRAGNN_INJECT_* family active_injections() reports
        from hydragnn_tpu.utils import knobs

        monkeypatch.setenv("HYDRAGNN_INJECT_GRAFTCHECK", "cc003")
        assert ir.active_injections() == {"cc003"}
        assert "HYDRAGNN_INJECT_GRAFTCHECK" in knobs.active_injections()

    def test_no_injection_by_default(self, monkeypatch):
        monkeypatch.delenv("HYDRAGNN_INJECT_GRAFTCHECK", raising=False)
        assert ir.active_injections() == set()


# --------------------------------------------------- baseline round-trip


class TestBaselineRoundTrip:
    def test_findings_fingerprint_through_baseline(self, tmp_path):
        f1 = ir._finding("CC001", "graftcheck/dp/train_step", "host transfer: x")
        f2 = ir._finding("CC005", "graftcheck/dp/train_step", "no donation marker")
        path = str(tmp_path / "baseline.json")
        write_baseline(path, [f1])
        grandfathered = load_baseline(path)
        assert f1.fingerprint() in grandfathered
        assert f2.fingerprint() not in grandfathered
        # the CLI's filter semantics: grandfathered findings drop
        remaining = [
            f for f in (f1, f2) if f.fingerprint() not in grandfathered
        ]
        assert remaining == [f2]

    def test_committed_baseline_is_empty(self):
        with open(os.path.join(REPO_ROOT, "tools", "graftcheck_baseline.json")) as fh:
            data = json.load(fh)
        assert data["findings"] == [], (
            "tools/graftcheck_baseline.json must stay empty — the shipped "
            "tree passes every CC contract"
        )


# ----------------------------------------------------- contract_block


class TestContractBlock:
    def test_no_module_is_all_not_checked(self):
        block = ir.contract_block(None)
        assert block["schema"] == ir.SCHEMA_VERSION
        assert set(block["contracts"]) == set(ir.CONTRACTS)
        assert all(
            c["status"] == "not_checked" for c in block["contracts"].values()
        )
        assert block["violations"] == []

    def test_clean_donated_module_passes(self):
        text = "func.func public @main(%arg0 {jax.buffer_donor = true})"
        block = ir.contract_block(text, donated=True)
        assert block["contracts"]["CC001"]["status"] == "pass"
        assert block["contracts"]["CC005"]["status"] == "pass"
        assert block["contracts"]["CC002"]["status"] == "not_checked"
        assert block["violations"] == []

    def test_violations_are_reported(self):
        text = (
            "stablehlo.custom_call @xla_python_cpu_callback(%x)\n"
            "func.func public @main(%arg0: tensor<8xf32>)"
        )
        block = ir.contract_block(text, donated=True)
        assert block["contracts"]["CC001"]["status"] == "fail"
        assert block["contracts"]["CC005"]["status"] == "fail"
        assert len(block["violations"]) == 2

    def test_compiled_text_enables_cc003(self):
        compiled = (
            "HloModule jit_step, input_output_alias={ {0}: (0, {}) }\n"
            "  %p = f32[8] collective-permute(%x), "
            "source_target_pairs={{0,1}}\n"
        )
        block = ir.contract_block(
            "tf.aliasing_output", donated=True, compiled_text=compiled, data=8
        )
        assert block["contracts"]["CC003"]["status"] == "fail"
        assert any("CC003" in v for v in block["violations"])


# --------------------------------------------------- shipped-tree meta


@pytest.mark.slow
class TestShippedTree:
    """The acceptance meta-tests: the shipped tree passes all six
    contracts under both CI layouts, and each injection is rejected by
    exactly its own contract. ci.sh's graftcheck stage runs the same
    proof from the CLI; these stay importable for full (non-tier-1)
    pytest runs."""

    def test_clean_under_both_layouts(self):
        findings = ir.run_graftcheck(
            layouts=("dp", "fsdp2"), contracts=None, inject=set()
        )
        assert findings == [], "\n".join(f.render() for f in findings)

    @pytest.mark.parametrize("cc", sorted(ir.INJECTABLE))
    def test_each_injection_is_rejected(self, cc):
        findings = ir.run_graftcheck(
            layouts=("dp",), contracts=[cc.upper()], inject={cc}
        )
        assert findings, f"injection {cc} was not rejected"
        assert {f.rule for f in findings} == {cc.upper()}
