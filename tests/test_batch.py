"""Unit tests for GraphBatch construction and padding invariants."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from hydragnn_tpu.graph import GraphBatch, batch_graphs, pad_batch, segment_mean


def tiny_graph(n, e_pairs, feat_offset=0.0):
    x = np.arange(n, dtype=np.float32)[:, None] + feat_offset
    s = np.array([p[0] for p in e_pairs], dtype=np.int32)
    r = np.array([p[1] for p in e_pairs], dtype=np.int32)
    return {
        "x": x,
        "senders": s,
        "receivers": r,
        "pos": np.random.RandomState(0).rand(n, 3).astype(np.float32),
        "graph_targets": {"energy": np.array([x.sum()])},
        "node_targets": {"charge": x * 2},
    }


def test_batch_graphs_basic():
    g1 = tiny_graph(3, [(0, 1), (1, 2)])
    g2 = tiny_graph(2, [(0, 1)], feat_offset=10.0)
    b = batch_graphs([g1, g2])

    assert b.num_graphs == 3  # 2 real + 1 padding slot
    assert bool(b.graph_mask[0]) and bool(b.graph_mask[1]) and not bool(b.graph_mask[2])
    np.testing.assert_array_equal(np.asarray(b.n_node[:2]), [3, 2])
    np.testing.assert_array_equal(np.asarray(b.n_edge[:2]), [2, 1])
    # second graph's edges are offset by 3 nodes
    assert int(b.senders[2]) == 3 and int(b.receivers[2]) == 4
    # padding nodes belong to padding graph
    assert int(b.node_graph[5]) == 2
    assert not bool(b.node_mask[5])
    # targets land in the right slots
    np.testing.assert_allclose(np.asarray(b.graph_targets["energy"][0]), [3.0])
    np.testing.assert_allclose(np.asarray(b.graph_targets["energy"][2]), [0.0])
    np.testing.assert_allclose(np.asarray(b.node_targets["charge"][3]), [20.0])


def test_padding_does_not_pollute_pooling():
    g1 = tiny_graph(3, [(0, 1), (1, 2)])
    g2 = tiny_graph(2, [(0, 1)], feat_offset=10.0)
    b = batch_graphs([g1, g2], n_node_pad=64, n_edge_pad=64, n_graph_pad=8)
    pooled = segment_mean(b.nodes, b.node_graph, b.num_graphs, mask=b.node_mask)
    np.testing.assert_allclose(np.asarray(pooled[0]), [1.0])  # mean(0,1,2)
    np.testing.assert_allclose(np.asarray(pooled[1]), [10.5])  # mean(10,11)


def test_pad_batch_roundtrip():
    g1 = tiny_graph(3, [(0, 1), (1, 2)])
    b = batch_graphs([g1])
    big = pad_batch(b, 32, 32, 4)
    assert big.num_nodes == 32 and big.num_edges == 32 and big.num_graphs == 4
    # real data unchanged
    np.testing.assert_allclose(np.asarray(big.nodes[:3, 0]), [0.0, 1.0, 2.0])
    # new padding edges point at a safe node, masked out
    assert not bool(big.edge_mask[-1])
    pooled = segment_mean(big.nodes, big.node_graph, 4, mask=big.node_mask)
    np.testing.assert_allclose(np.asarray(pooled[0]), [1.0])


def test_1d_targets_and_edge_attr_normalized():
    # 1-D node targets / edge_attr must become [n,1] columns, not broadcast.
    g = {
        "x": np.ones((3,), np.float32),
        "senders": np.array([0, 1], np.int32),
        "receivers": np.array([1, 2], np.int32),
        "edge_attr": np.array([5.0, 6.0], np.float32),
        "graph_targets": {"e": np.array([1.0])},
        "node_targets": {"q": np.array([1.0, 2.0, 3.0], np.float32)},
    }
    b = batch_graphs([g])
    assert b.node_targets["q"].shape[1] == 1
    np.testing.assert_allclose(np.asarray(b.node_targets["q"][:3, 0]), [1, 2, 3])
    assert b.edge_attr.shape[1] == 1
    np.testing.assert_allclose(np.asarray(b.edge_attr[:2, 0]), [5, 6])


def test_pad_batch_partial_growth_keeps_indices_in_range():
    g1 = tiny_graph(3, [(0, 1), (1, 2)])
    b = batch_graphs([g1])
    # grow only nodes: new padding nodes must use the existing padding graph
    nb = pad_batch(b, b.num_nodes + 5, b.num_edges, b.num_graphs)
    assert int(np.asarray(nb.node_graph).max()) < nb.num_graphs
    # grow only edges: new padding edges must point at an existing padding node
    eb = pad_batch(b, b.num_nodes, b.num_edges + 5, b.num_graphs)
    assert int(np.asarray(eb.senders).max()) < eb.num_nodes
    assert not bool(eb.node_mask[int(np.asarray(eb.senders)[-1])])


def test_heterogeneous_fields_rejected():
    import pytest

    g1 = tiny_graph(2, [(0, 1)])
    g2 = tiny_graph(2, [(0, 1)])
    del g2["pos"]
    g2["pos"] = None
    with pytest.raises(ValueError):
        batch_graphs([g1, g2])
    with pytest.raises(ValueError):
        batch_graphs([])


def test_graphbatch_is_pytree():
    g1 = tiny_graph(2, [(0, 1)])
    b = batch_graphs([g1])
    leaves = jax.tree_util.tree_leaves(b)
    assert all(hasattr(l, "shape") for l in leaves)

    @jax.jit
    def f(batch: GraphBatch):
        return batch.nodes.sum()

    assert np.isfinite(float(f(b)))


def test_check_invariants_all_construction_paths():
    """batch_graphs / pad_batch (both growth shapes) / _mask_out maintain
    every loader contract check_invariants validates — including the
    precomputed perms, degrees, and local-window plans (r03 advisor:
    external batch producers should fail loudly, so the checker itself
    must pass the canonical constructors)."""
    from hydragnn_tpu.data.loader import _mask_out

    rng = np.random.default_rng(3)
    gs = []
    for _ in range(6):
        n = int(rng.integers(4, 9))
        s = np.arange(n)
        r = (s + 1) % n
        gs.append(
            {
                "x": rng.standard_normal((n, 3)),
                "senders": s,
                "receivers": r,
                "graph_targets": {"e": rng.standard_normal(1)},
            }
        )
    b = batch_graphs(gs, dense_slots=4)
    b.check_invariants()
    pad_batch(b, b.num_nodes + 16, b.num_edges + 8, b.num_graphs + 2).check_invariants()
    pad_batch(b, b.num_nodes, b.num_edges + 8, b.num_graphs).check_invariants()
    _mask_out(b).check_invariants()

    # a violated contract is caught: masked edge pointed at a real node
    bad_recv = np.asarray(b.receivers).copy()
    bad_recv[-1] = 0  # the tail padding edge now targets real node 0
    bad = b.replace(receivers=jnp.asarray(np.sort(bad_recv)), in_degree=None)
    with pytest.raises(AssertionError):
        bad.check_invariants()


def test_loader_debug_mode_catches_corrupt_producer(monkeypatch):
    """HYDRAGNN_DEBUG_BATCH=1 makes the loader validate every host batch,
    so a corrupt external sample producer fails loudly instead of
    silently corrupting aggregations (r03 advisor)."""
    from hydragnn_tpu.data import loader as loader_mod
    from hydragnn_tpu.data.dataset import GraphSample

    rng = np.random.default_rng(5)
    samples = []
    for _ in range(4):
        n = 5
        s = np.arange(n)
        r = (s + 1) % n
        samples.append(
            GraphSample(
                x=rng.standard_normal((n, 2)).astype(np.float32),
                edge_index=np.stack([s, r]).astype(np.int32),
                graph_targets={"e": rng.standard_normal(1).astype(np.float32)},
            )
        )

    real_batch_graphs = loader_mod.batch_graphs

    def corrupting_batch_graphs(*args, **kwargs):
        b = real_batch_graphs(*args, **kwargs)
        bad_recv = np.asarray(b.receivers).copy()
        bad_recv[-1] = 0  # tail padding edge retargeted at a real node
        return b.replace(receivers=jnp.asarray(np.sort(bad_recv)), in_degree=None)

    monkeypatch.setattr(loader_mod, "batch_graphs", corrupting_batch_graphs)

    # default (debug off): the corruption passes through silently
    monkeypatch.delenv("HYDRAGNN_DEBUG_BATCH", raising=False)
    ldr = loader_mod.GraphLoader(samples, batch_size=4, prefetch=0)
    assert len(list(ldr)) == 1

    # debug on: the same producer fails loudly at batch build time
    monkeypatch.setenv("HYDRAGNN_DEBUG_BATCH", "1")
    ldr = loader_mod.GraphLoader(samples, batch_size=4, prefetch=0)
    with pytest.raises(AssertionError):
        list(ldr)
