"""SMILES featurizer tests (reference behavior:
hydragnn/utils/smiles_utils.py:18-119 via RDKit; here a native parser).

Assertions check hydrogen-complete formulas, feature layout, bond classes,
and H-neighbor counts against hand-computed chemistry.
"""

import numpy as np
import pytest

from hydragnn_tpu.data.smiles import (
    SmilesParseError,
    generate_graphdata_from_smilestr,
    get_node_attribute_name,
    mol_from_smiles,
    molecular_formula,
    parse_smiles,
)
from hydragnn_tpu.data.atomic_descriptors import atomicdescriptors

TYPES = {"C": 0, "H": 1, "O": 2, "N": 3, "F": 4, "S": 5}


@pytest.mark.parametrize(
    "smiles,formula",
    [
        ("C", {"C": 1, "H": 4}),                      # methane
        ("CC", {"C": 2, "H": 6}),                     # ethane
        ("C=C", {"C": 2, "H": 4}),                    # ethene
        ("C#N", {"C": 1, "N": 1, "H": 1}),            # HCN
        ("CO", {"C": 1, "O": 1, "H": 4}),             # methanol
        ("c1ccccc1", {"C": 6, "H": 6}),               # benzene
        ("c1ccncc1", {"C": 5, "N": 1, "H": 5}),       # pyridine
        ("c1cc[nH]c1", {"C": 4, "N": 1, "H": 5}),     # pyrrole
        ("c1ccoc1", {"C": 4, "O": 1, "H": 4}),        # furan
        ("Cc1ccccc1", {"C": 7, "H": 8}),              # toluene
        ("CC(=O)O", {"C": 2, "O": 2, "H": 4}),        # acetic acid
        ("C1CC1", {"C": 3, "H": 6}),                  # cyclopropane
        ("[NH4+]", {"N": 1, "H": 4}),                 # bracket atom + charge
        ("O.O", {"O": 2, "H": 4}),                    # disconnected waters
        ("N#N", {"N": 2}),                            # dinitrogen
        ("CS(=O)(=O)C", {"C": 2, "S": 1, "O": 2, "H": 6}),  # DMSO2 (S valence 6)
    ],
)
def pytest_formula(smiles, formula):
    assert molecular_formula(mol_from_smiles(smiles)) == formula


def pytest_parse_errors():
    for bad in ["C(", "C)", "C1CC", "[C", "Cl(", "Xx", "C%1"]:
        with pytest.raises((SmilesParseError, ValueError)):
            mol_from_smiles(bad)


def pytest_ring_closure_percent():
    # %12-style two-digit ring closure
    atoms, bonds = parse_smiles("C%12CCCCC%12")
    assert len(atoms) == 6 and len(bonds) == 6


def pytest_feature_layout_methane():
    g = generate_graphdata_from_smilestr("C", np.array([1.5]), TYPES)
    # 1 C + 4 H, features = 6 one-hot + [Z, aromatic, sp, sp2, sp3, numHs]
    assert g.x.shape == (5, len(TYPES) + 6)
    c = g.x[0]
    assert c[0] == 1.0 and c[len(TYPES)] == 6  # one-hot C, Z=6
    assert c[len(TYPES) + 1] == 0  # not aromatic
    assert tuple(c[len(TYPES) + 2 : len(TYPES) + 5]) == (0, 0, 1)  # sp3
    assert c[len(TYPES) + 5] == 4  # 4 H neighbors
    for h in g.x[1:]:
        assert h[1] == 1.0 and h[len(TYPES)] == 1
    # 4 bonds, both directions
    assert g.edge_index.shape == (2, 8)
    # all single bonds -> class 0
    assert np.all(g.edge_attr[:, 0] == 1)
    # sorted by sender*N+receiver like the reference (smiles_utils.py:83-85)
    key = g.edge_index[0] * 5 + g.edge_index[1]
    assert np.all(np.diff(key) > 0)


def pytest_hybridization_and_aromatic():
    g = generate_graphdata_from_smilestr("c1ccccc1", np.array([0.0]), TYPES)
    ring = g.x[:6]
    assert np.all(ring[:, len(TYPES) + 1] == 1)  # aromatic
    assert np.all(ring[:, len(TYPES) + 3] == 1)  # sp2
    # aromatic bond class 3 present
    arom_edges = g.edge_attr[:, 3].sum()
    assert arom_edges == 12  # 6 ring bonds x 2 directions

    g2 = generate_graphdata_from_smilestr("C#N", np.array([0.0]), TYPES)
    assert g2.x[0, len(TYPES) + 2] == 1  # C is sp
    assert g2.edge_attr[:, 2].sum() == 2  # one triple bond, 2 directions


def pytest_graph_target_and_descriptors(tmp_path):
    desc = atomicdescriptors(str(tmp_path / "emb.json"), element_types=["C", "H", "O"])
    g0 = generate_graphdata_from_smilestr("CO", np.array([2.0]), TYPES)
    table = np.stack(
        [desc.get_atom_features(int(z)) for z in g0.x[:, len(TYPES)]]
    )
    g = generate_graphdata_from_smilestr("CO", np.array([2.0]), TYPES,
                                         atomic_descriptors=table)
    assert g.graph_y.tolist() == [2.0]
    assert g.x.shape[1] == len(TYPES) + 6 + table.shape[1]


def pytest_node_attribute_names():
    names, dims = get_node_attribute_name(TYPES)
    assert names[:2] == ["atomC", "atomH"]
    assert names[-1] == "Hprop" and all(d == 1 for d in dims)


def pytest_descriptor_table(tmp_path):
    d = atomicdescriptors(str(tmp_path / "e.json"),
                          element_types=["C", "H", "S"])
    fc = d.get_atom_features("C")
    # 3 type one-hot + group + period + radius + EA + 4 block + volume + Z
    # + weight + EN + nvalence + ion = 3 + 1*10 + 4 = 17
    assert fc.shape == (17,)
    assert d.get_atom_features(6).tolist() == fc.tolist()
    # reload path (overwritten=False)
    d2 = atomicdescriptors(str(tmp_path / "e.json"), overwritten=False)
    assert d2.get_atom_features("S").shape == (17,)
    # one-hot mode: all entries binary
    d3 = atomicdescriptors(str(tmp_path / "e1h.json"),
                           element_types=["C", "H", "S"], one_hot=True)
    f1h = d3.get_atom_features("H")
    assert set(np.unique(f1h)).issubset({0.0, 1.0})
