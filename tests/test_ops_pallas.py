"""Fused sum-family aggregation: XLA fused pass and Pallas kernel
(interpret mode on CPU) must match the plain per-op reference."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from hydragnn_tpu.ops import (
    segment_sum_family_pallas,
    segment_sum_family_xla,
)


@pytest.fixture
def case():
    rng = np.random.default_rng(5)
    e, h, n = 700, 16, 100
    recv = np.sort(rng.integers(0, n, e)).astype(np.int32)
    data = rng.normal(size=(e, h)).astype(np.float32)
    mask = rng.random(e) > 0.2
    return jnp.asarray(data), jnp.asarray(recv), n, jnp.asarray(mask)


def _reference(data, recv, n, mask):
    m = np.asarray(mask)[:, None]
    d = np.asarray(data) * m
    s = np.zeros((n, d.shape[1]), np.float64)
    sq = np.zeros((n, d.shape[1]), np.float64)
    c = np.zeros(n, np.float64)
    np.add.at(s, np.asarray(recv), d)
    np.add.at(sq, np.asarray(recv), d * d)
    np.add.at(c, np.asarray(recv), m[:, 0].astype(np.float64))
    return s, sq, c


def pytest_xla_family_matches_reference(case):
    data, recv, n, mask = case
    s, sq, c = segment_sum_family_xla(data, recv, n, mask)
    rs, rsq, rc = _reference(data, recv, n, mask)
    np.testing.assert_allclose(s, rs, rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(sq, rsq, rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(c, rc, rtol=1e-6)


def pytest_pallas_family_matches_reference(case):
    data, recv, n, mask = case
    s, sq, c = segment_sum_family_pallas(data, recv, n, mask, interpret=True)
    rs, rsq, rc = _reference(data, recv, n, mask)
    np.testing.assert_allclose(s, rs, rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(sq, rsq, rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(c, rc, rtol=1e-6)


def pytest_pallas_family_no_mask_multi_chunk():
    """More edges than one CE chunk per block, empty segments included."""
    rng = np.random.default_rng(7)
    e, h, n = 3000, 8, 40  # ~75 edges/node; block 0 covers all 40 nodes
    recv = np.sort(rng.integers(0, n // 2, e)).astype(np.int32)  # half empty
    data = rng.normal(size=(e, h)).astype(np.float32)
    s, sq, c = segment_sum_family_pallas(
        jnp.asarray(data), jnp.asarray(recv), n, None, interpret=True
    )
    rs, rsq, rc = _reference(data, recv, n, np.ones(e, bool))
    np.testing.assert_allclose(s, rs, rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(sq, rsq, rtol=1e-3, atol=1e-4)
    np.testing.assert_allclose(c, rc, rtol=1e-6)


def pytest_xla_family_unsorted_ids():
    """The default path must be correct for sender-major (unsorted
    receiver) edge orderings, e.g. SMILES-featurized graphs."""
    rng = np.random.default_rng(9)
    e, h, n = 500, 8, 60
    recv = rng.integers(0, n, e).astype(np.int32)  # deliberately unsorted
    data = rng.normal(size=(e, h)).astype(np.float32)
    s, sq, c = segment_sum_family_xla(jnp.asarray(data), jnp.asarray(recv), n)
    rs, rsq, rc = _reference(data, recv, n, np.ones(e, bool))
    np.testing.assert_allclose(s, rs, rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(c, rc, rtol=1e-6)


def pytest_pallas_family_unsorted_ids_sorts():
    """Default indices_are_sorted=False must be correct for sender-major
    orderings (the kernel sorts internally)."""
    rng = np.random.default_rng(13)
    e, h, n = 600, 8, 70
    recv = rng.integers(0, n, e).astype(np.int32)  # deliberately unsorted
    data = rng.normal(size=(e, h)).astype(np.float32)
    s, sq, c = segment_sum_family_pallas(
        jnp.asarray(data), jnp.asarray(recv), n, None, interpret=True
    )
    rs, rsq, rc = _reference(data, recv, n, np.ones(e, bool))
    np.testing.assert_allclose(s, rs, rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(c, rc, rtol=1e-6)


def pytest_family_accumulates_f32_under_bf16():
    """bf16 inputs: mean/var cancellation must not collapse (f32 accum)."""
    rng = np.random.default_rng(21)
    e, n = 512, 4
    recv = np.sort(rng.integers(0, n, e)).astype(np.int32)
    # mean 8, spread 0.2: representable in bf16 (ulp ~0.03) but a ~128-term
    # bf16 running sum (~1000, ulp ~4) would drown the contributions;
    # f32 accumulation must preserve the variance's order of magnitude
    data = (8.0 + 0.2 * rng.normal(size=(e, 8))).astype(np.float32)
    s, sq, c = segment_sum_family_xla(
        jnp.asarray(data, dtype=jnp.bfloat16), jnp.asarray(recv), n
    )
    mean = np.asarray(s) / np.asarray(c)[:, None]
    var = np.asarray(sq) / np.asarray(c)[:, None] - mean**2
    assert np.all(var > 5e-3), var.min()
    assert np.all(var < 1e-1), var.max()


def pytest_family_custom_vjp_matches_autodiff():
    """segment_sum_family routes ALL training gradients through the
    hand-written gather VJP; it must equal autodiff of the mathematical
    definition (masked sum / sum-of-squares), including masked rows."""
    rng = np.random.default_rng(3)
    e, h, n = 300, 8, 40
    data = jnp.asarray(rng.normal(size=(e, h)).astype(np.float32))
    seg = jnp.asarray(np.sort(rng.integers(0, n, e)).astype(np.int32))
    mask = jnp.asarray(rng.random(e) > 0.2)

    from hydragnn_tpu.ops import segment_sum_family

    def via_custom(d):
        s, sq, c = segment_sum_family(d, seg, n, mask=mask, indices_are_sorted=True)
        return (s * 1.3).sum() + (sq * 0.7).sum() + c.sum()

    def via_autodiff(d):
        m = mask[:, None].astype(jnp.float32)
        dm = d * m
        s = jax.ops.segment_sum(dm, seg, n)
        sq = jax.ops.segment_sum(dm * dm, seg, n)
        c = jax.ops.segment_sum(m[:, 0], seg, n)
        return (s * 1.3).sum() + (sq * 0.7).sum() + c.sum()

    np.testing.assert_allclose(
        float(via_custom(data)), float(via_autodiff(data)), rtol=1e-5
    )
    g_custom = jax.grad(via_custom)(data)
    g_auto = jax.grad(via_autodiff)(data)
    np.testing.assert_allclose(
        np.asarray(g_custom), np.asarray(g_auto), rtol=1e-5, atol=1e-6
    )
    # masked rows receive exactly zero gradient
    assert not np.asarray(g_custom)[~np.asarray(mask)].any()

    # no-mask path
    g2 = jax.grad(lambda d: segment_sum_family(d, seg, n)[1].sum())(data)
    g2_ref = jax.grad(lambda d: jax.ops.segment_sum(d * d, seg, n).sum())(data)
    np.testing.assert_allclose(np.asarray(g2), np.asarray(g2_ref), rtol=1e-5, atol=1e-6)


def pytest_sum_kernel_interpret_matches_xla():
    """The sum-only CSR kernel (VJP hot path) against jax.ops.segment_sum,
    interpret mode, masked + unsorted-input coverage."""
    from hydragnn_tpu.ops.segment_pallas import segment_sum_pallas

    rng = np.random.default_rng(5)
    e, h, n = 700, 128, 150
    data = jnp.asarray(rng.normal(size=(e, h)).astype(np.float32))
    seg_sorted = jnp.asarray(np.sort(rng.integers(0, n, e)).astype(np.int32))
    mask = jnp.asarray(rng.random(e) > 0.3)

    ref = jax.ops.segment_sum(data * mask[:, None], seg_sorted, n)
    out = segment_sum_pallas(
        data, seg_sorted, n, mask=mask, interpret=True, indices_are_sorted=True
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-5, atol=1e-5)

    seg_rand = jnp.asarray(rng.integers(0, n, e).astype(np.int32))
    ref2 = jax.ops.segment_sum(data, seg_rand, n)
    out2 = segment_sum_pallas(data, seg_rand, n, interpret=True)
    np.testing.assert_allclose(np.asarray(out2), np.asarray(ref2), rtol=1e-5, atol=1e-5)


def pytest_gather_rows_grad_matches_plain_gather():
    """gather_rows must be value- and gradient-identical to x[ids]."""
    from hydragnn_tpu.graph.segment import gather_rows

    rng = np.random.default_rng(7)
    n, h, e = 60, 16, 400
    x = jnp.asarray(rng.normal(size=(n, h)).astype(np.float32))
    ids = jnp.asarray(np.sort(rng.integers(0, n, e)).astype(np.int32))
    w = jnp.asarray(rng.normal(size=(e, h)).astype(np.float32))

    np.testing.assert_array_equal(
        np.asarray(gather_rows(x, ids, n, True)), np.asarray(x[ids])
    )
    g_custom = jax.grad(lambda xx: (gather_rows(xx, ids, n, True) * w).sum())(x)
    g_plain = jax.grad(lambda xx: (xx[ids] * w).sum())(x)
    np.testing.assert_allclose(
        np.asarray(g_custom), np.asarray(g_plain), rtol=1e-5, atol=1e-6
    )


def pytest_gather_rows_permuted_grad_matches_plain():
    """gather_rows_permuted (unsorted ids + precomputed argsort) must be
    value- and gradient-identical to x[ids]."""
    from hydragnn_tpu.graph.segment import gather_rows_permuted

    rng = np.random.default_rng(9)
    n, h, e = 60, 16, 400
    x = jnp.asarray(rng.normal(size=(n, h)).astype(np.float32))
    ids = jnp.asarray(rng.integers(0, n, e).astype(np.int32))  # unsorted
    perm = jnp.argsort(ids)
    w = jnp.asarray(rng.normal(size=(e, h)).astype(np.float32))

    np.testing.assert_array_equal(
        np.asarray(gather_rows_permuted(x, ids, perm, n)), np.asarray(x[ids])
    )
    g_custom = jax.grad(
        lambda xx: (gather_rows_permuted(xx, ids, perm, n) * w).sum()
    )(x)
    g_plain = jax.grad(lambda xx: (xx[ids] * w).sum())(x)
    np.testing.assert_allclose(
        np.asarray(g_custom), np.asarray(g_plain), rtol=1e-5, atol=1e-6
    )


def pytest_family_pallas_bf16_path():
    """The kernel's bf16 DMA path: bf16 inputs, f32 accumulation — must
    match the XLA family on the same bf16 data (interpret mode), and a
    non-boolean weight mask must not be double-rounded."""
    from hydragnn_tpu.ops.segment_pallas import (
        segment_sum_family_pallas,
        segment_sum_family_xla,
        segment_sum_pallas,
    )

    rng = np.random.default_rng(11)
    e, h, n = 700, 128, 150
    data = jnp.asarray(rng.normal(size=(e, h)).astype(np.float32)).astype(jnp.bfloat16)
    seg = jnp.asarray(np.sort(rng.integers(0, n, e)).astype(np.int32))
    mask = jnp.asarray(rng.random(e) > 0.3)

    s_ref, sq_ref, c_ref = segment_sum_family_xla(data, seg, n, mask=mask)
    s_out, sq_out, c_out = segment_sum_family_pallas(
        data, seg, n, mask=mask, interpret=True, indices_are_sorted=True
    )
    np.testing.assert_allclose(np.asarray(s_out), np.asarray(s_ref), rtol=1e-4, atol=1e-3)
    np.testing.assert_allclose(np.asarray(sq_out), np.asarray(sq_ref), rtol=1e-4, atol=1e-3)
    np.testing.assert_array_equal(np.asarray(c_out), np.asarray(c_ref))
    # outputs accumulate f32 even from bf16 inputs
    assert s_out.dtype == jnp.float32 and sq_out.dtype == jnp.float32

    # float weight mask with bf16 data: premultiply happens in f32
    wmask = jnp.asarray(rng.random(e).astype(np.float32))
    ref = jax.ops.segment_sum(
        (data.astype(jnp.float32) * wmask[:, None]).astype(jnp.bfloat16).astype(jnp.float32),
        seg, n,
    )
    out = segment_sum_pallas(
        data, seg, n, mask=wmask, interpret=True, indices_are_sorted=True
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-4, atol=1e-3)
