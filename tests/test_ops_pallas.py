"""Fused sum-family aggregation: XLA fused pass and Pallas kernel
(interpret mode on CPU) must match the plain per-op reference."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from hydragnn_tpu.utils.jax_compat import shard_map
from hydragnn_tpu.ops import (
    segment_sum_family_pallas,
    segment_sum_family_xla,
)


@pytest.fixture
def case():
    rng = np.random.default_rng(5)
    e, h, n = 700, 16, 100
    recv = np.sort(rng.integers(0, n, e)).astype(np.int32)
    data = rng.normal(size=(e, h)).astype(np.float32)
    mask = rng.random(e) > 0.2
    return jnp.asarray(data), jnp.asarray(recv), n, jnp.asarray(mask)


def _reference(data, recv, n, mask):
    m = np.asarray(mask)[:, None]
    d = np.asarray(data) * m
    s = np.zeros((n, d.shape[1]), np.float64)
    sq = np.zeros((n, d.shape[1]), np.float64)
    c = np.zeros(n, np.float64)
    np.add.at(s, np.asarray(recv), d)
    np.add.at(sq, np.asarray(recv), d * d)
    np.add.at(c, np.asarray(recv), m[:, 0].astype(np.float64))
    return s, sq, c


def pytest_xla_family_matches_reference(case):
    data, recv, n, mask = case
    s, sq, c = segment_sum_family_xla(data, recv, n, mask)
    rs, rsq, rc = _reference(data, recv, n, mask)
    np.testing.assert_allclose(s, rs, rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(sq, rsq, rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(c, rc, rtol=1e-6)


def pytest_pallas_family_matches_reference(case):
    data, recv, n, mask = case
    s, sq, c = segment_sum_family_pallas(data, recv, n, mask, interpret=True)
    rs, rsq, rc = _reference(data, recv, n, mask)
    np.testing.assert_allclose(s, rs, rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(sq, rsq, rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(c, rc, rtol=1e-6)


def pytest_pallas_family_no_mask_multi_chunk():
    """More edges than one CE chunk per block, empty segments included."""
    rng = np.random.default_rng(7)
    e, h, n = 3000, 8, 40  # ~75 edges/node; block 0 covers all 40 nodes
    recv = np.sort(rng.integers(0, n // 2, e)).astype(np.int32)  # half empty
    data = rng.normal(size=(e, h)).astype(np.float32)
    s, sq, c = segment_sum_family_pallas(
        jnp.asarray(data), jnp.asarray(recv), n, None, interpret=True
    )
    rs, rsq, rc = _reference(data, recv, n, np.ones(e, bool))
    np.testing.assert_allclose(s, rs, rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(sq, rsq, rtol=1e-3, atol=1e-4)
    np.testing.assert_allclose(c, rc, rtol=1e-6)


def pytest_xla_family_unsorted_ids():
    """The default path must be correct for sender-major (unsorted
    receiver) edge orderings, e.g. SMILES-featurized graphs."""
    rng = np.random.default_rng(9)
    e, h, n = 500, 8, 60
    recv = rng.integers(0, n, e).astype(np.int32)  # deliberately unsorted
    data = rng.normal(size=(e, h)).astype(np.float32)
    s, sq, c = segment_sum_family_xla(jnp.asarray(data), jnp.asarray(recv), n)
    rs, rsq, rc = _reference(data, recv, n, np.ones(e, bool))
    np.testing.assert_allclose(s, rs, rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(c, rc, rtol=1e-6)


def pytest_pallas_family_unsorted_ids_sorts():
    """Default indices_are_sorted=False must be correct for sender-major
    orderings (the kernel sorts internally)."""
    rng = np.random.default_rng(13)
    e, h, n = 600, 8, 70
    recv = rng.integers(0, n, e).astype(np.int32)  # deliberately unsorted
    data = rng.normal(size=(e, h)).astype(np.float32)
    s, sq, c = segment_sum_family_pallas(
        jnp.asarray(data), jnp.asarray(recv), n, None, interpret=True
    )
    rs, rsq, rc = _reference(data, recv, n, np.ones(e, bool))
    np.testing.assert_allclose(s, rs, rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(c, rc, rtol=1e-6)


def pytest_family_accumulates_f32_under_bf16():
    """bf16 inputs: mean/var cancellation must not collapse (f32 accum)."""
    rng = np.random.default_rng(21)
    e, n = 512, 4
    recv = np.sort(rng.integers(0, n, e)).astype(np.int32)
    # mean 8, spread 0.2: representable in bf16 (ulp ~0.03) but a ~128-term
    # bf16 running sum (~1000, ulp ~4) would drown the contributions;
    # f32 accumulation must preserve the variance's order of magnitude
    data = (8.0 + 0.2 * rng.normal(size=(e, 8))).astype(np.float32)
    s, sq, c = segment_sum_family_xla(
        jnp.asarray(data, dtype=jnp.bfloat16), jnp.asarray(recv), n
    )
    mean = np.asarray(s) / np.asarray(c)[:, None]
    var = np.asarray(sq) / np.asarray(c)[:, None] - mean**2
    assert np.all(var > 5e-3), var.min()
    assert np.all(var < 1e-1), var.max()


def pytest_family_custom_vjp_matches_autodiff():
    """segment_sum_family routes ALL training gradients through the
    hand-written gather VJP; it must equal autodiff of the mathematical
    definition (masked sum / sum-of-squares), including masked rows."""
    rng = np.random.default_rng(3)
    e, h, n = 300, 8, 40
    data = jnp.asarray(rng.normal(size=(e, h)).astype(np.float32))
    seg = jnp.asarray(np.sort(rng.integers(0, n, e)).astype(np.int32))
    mask = jnp.asarray(rng.random(e) > 0.2)

    from hydragnn_tpu.ops import segment_sum_family

    def via_custom(d):
        s, sq, c = segment_sum_family(d, seg, n, mask=mask, indices_are_sorted=True)
        return (s * 1.3).sum() + (sq * 0.7).sum() + c.sum()

    def via_autodiff(d):
        m = mask[:, None].astype(jnp.float32)
        dm = d * m
        s = jax.ops.segment_sum(dm, seg, n)
        sq = jax.ops.segment_sum(dm * dm, seg, n)
        c = jax.ops.segment_sum(m[:, 0], seg, n)
        return (s * 1.3).sum() + (sq * 0.7).sum() + c.sum()

    np.testing.assert_allclose(
        float(via_custom(data)), float(via_autodiff(data)), rtol=1e-5
    )
    g_custom = jax.grad(via_custom)(data)
    g_auto = jax.grad(via_autodiff)(data)
    np.testing.assert_allclose(
        np.asarray(g_custom), np.asarray(g_auto), rtol=1e-5, atol=1e-6
    )
    # masked rows receive exactly zero gradient
    assert not np.asarray(g_custom)[~np.asarray(mask)].any()

    # no-mask path
    g2 = jax.grad(lambda d: segment_sum_family(d, seg, n)[1].sum())(data)
    g2_ref = jax.grad(lambda d: jax.ops.segment_sum(d * d, seg, n).sum())(data)
    np.testing.assert_allclose(np.asarray(g2), np.asarray(g2_ref), rtol=1e-5, atol=1e-6)


def pytest_sum_kernel_interpret_matches_xla():
    """The sum-only CSR kernel (VJP hot path) against jax.ops.segment_sum,
    interpret mode, masked + unsorted-input coverage."""
    from hydragnn_tpu.ops.segment_pallas import segment_sum_pallas

    rng = np.random.default_rng(5)
    e, h, n = 700, 128, 150
    data = jnp.asarray(rng.normal(size=(e, h)).astype(np.float32))
    seg_sorted = jnp.asarray(np.sort(rng.integers(0, n, e)).astype(np.int32))
    mask = jnp.asarray(rng.random(e) > 0.3)

    ref = jax.ops.segment_sum(data * mask[:, None], seg_sorted, n)
    out = segment_sum_pallas(
        data, seg_sorted, n, mask=mask, interpret=True, indices_are_sorted=True
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-5, atol=1e-5)

    seg_rand = jnp.asarray(rng.integers(0, n, e).astype(np.int32))
    ref2 = jax.ops.segment_sum(data, seg_rand, n)
    out2 = segment_sum_pallas(data, seg_rand, n, interpret=True)
    np.testing.assert_allclose(np.asarray(out2), np.asarray(ref2), rtol=1e-5, atol=1e-5)


def pytest_gather_rows_grad_matches_plain_gather():
    """gather_rows must be value- and gradient-identical to x[ids]."""
    from hydragnn_tpu.graph.segment import gather_rows

    rng = np.random.default_rng(7)
    n, h, e = 60, 16, 400
    x = jnp.asarray(rng.normal(size=(n, h)).astype(np.float32))
    ids = jnp.asarray(np.sort(rng.integers(0, n, e)).astype(np.int32))
    w = jnp.asarray(rng.normal(size=(e, h)).astype(np.float32))

    np.testing.assert_array_equal(
        np.asarray(gather_rows(x, ids, n, True)), np.asarray(x[ids])
    )
    g_custom = jax.grad(lambda xx: (gather_rows(xx, ids, n, True) * w).sum())(x)
    g_plain = jax.grad(lambda xx: (xx[ids] * w).sum())(x)
    np.testing.assert_allclose(
        np.asarray(g_custom), np.asarray(g_plain), rtol=1e-5, atol=1e-6
    )


def pytest_gather_rows_permuted_grad_matches_plain():
    """gather_rows_permuted (unsorted ids + precomputed argsort) must be
    value- and gradient-identical to x[ids]."""
    from hydragnn_tpu.graph.segment import gather_rows_permuted

    rng = np.random.default_rng(9)
    n, h, e = 60, 16, 400
    x = jnp.asarray(rng.normal(size=(n, h)).astype(np.float32))
    ids = jnp.asarray(rng.integers(0, n, e).astype(np.int32))  # unsorted
    perm = jnp.argsort(ids)
    w = jnp.asarray(rng.normal(size=(e, h)).astype(np.float32))

    np.testing.assert_array_equal(
        np.asarray(gather_rows_permuted(x, ids, perm, n)), np.asarray(x[ids])
    )
    g_custom = jax.grad(
        lambda xx: (gather_rows_permuted(xx, ids, perm, n) * w).sum()
    )(x)
    g_plain = jax.grad(lambda xx: (xx[ids] * w).sum())(x)
    np.testing.assert_allclose(
        np.asarray(g_custom), np.asarray(g_plain), rtol=1e-5, atol=1e-6
    )


def pytest_family_pallas_bf16_path():
    """The kernel's bf16 DMA path: bf16 inputs, f32 accumulation — must
    match the XLA family on the same bf16 data (interpret mode), and a
    non-boolean weight mask must not be double-rounded."""
    from hydragnn_tpu.ops.segment_pallas import (
        segment_sum_family_pallas,
        segment_sum_family_xla,
        segment_sum_pallas,
    )

    rng = np.random.default_rng(11)
    e, h, n = 700, 128, 150
    data = jnp.asarray(rng.normal(size=(e, h)).astype(np.float32)).astype(jnp.bfloat16)
    seg = jnp.asarray(np.sort(rng.integers(0, n, e)).astype(np.int32))
    mask = jnp.asarray(rng.random(e) > 0.3)

    s_ref, sq_ref, c_ref = segment_sum_family_xla(data, seg, n, mask=mask)
    s_out, sq_out, c_out = segment_sum_family_pallas(
        data, seg, n, mask=mask, interpret=True, indices_are_sorted=True
    )
    np.testing.assert_allclose(np.asarray(s_out), np.asarray(s_ref), rtol=1e-4, atol=1e-3)
    np.testing.assert_allclose(np.asarray(sq_out), np.asarray(sq_ref), rtol=1e-4, atol=1e-3)
    np.testing.assert_array_equal(np.asarray(c_out), np.asarray(c_ref))
    # outputs accumulate f32 even from bf16 inputs
    assert s_out.dtype == jnp.float32 and sq_out.dtype == jnp.float32

    # float weight mask with bf16 data: the kernel promotes to f32 (the
    # weighted products are not bf16-representable; on-chip selfcheck
    # divergence at realistic degrees) — reference is the pure-f32 product
    wmask = jnp.asarray(rng.random(e).astype(np.float32))
    ref = jax.ops.segment_sum(
        data.astype(jnp.float32) * wmask[:, None],
        seg, n,
    )
    out = segment_sum_pallas(
        data, seg, n, mask=wmask, interpret=True, indices_are_sorted=True
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-4, atol=1e-3)


def pytest_partitioned_family_edge_sharded_mesh(monkeypatch):
    """The custom_partitioning rule (VERDICT r02 item 2): the family
    kernel over operands GSPMD-sharded on the edge axis must run
    per-shard (local CSR + psum) and match the unsharded reference —
    interpret mode forced via HYDRAGNN_PALLAS=interpret on the 8-device
    CPU mesh."""
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from hydragnn_tpu.ops import segment_sum_family

    rng = np.random.default_rng(17)
    e, h, n = 1024, 128, 96  # e divisible by 8
    data = jnp.asarray(rng.normal(size=(e, h)).astype(np.float32))
    seg = jnp.asarray(np.sort(rng.integers(0, n, e)).astype(np.int32))
    mask = jnp.asarray(rng.random(e) > 0.25)

    mesh = Mesh(np.array(jax.devices()[:8]), ("data",))
    sh = NamedSharding(mesh, P("data"))
    data_s = jax.device_put(data, NamedSharding(mesh, P("data", None)))
    seg_s = jax.device_put(seg, sh)
    mask_s = jax.device_put(mask, sh)

    s_ref, sq_ref, c_ref = segment_sum_family_xla(data, seg, n, mask=mask)

    monkeypatch.setenv("HYDRAGNN_PALLAS", "interpret")
    fn = jax.jit(
        lambda d, i, m: segment_sum_family(d, i, n, mask=m, indices_are_sorted=True)
    )
    s, sq, c = fn(data_s, seg_s, mask_s)
    np.testing.assert_allclose(np.asarray(s), np.asarray(s_ref), rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(np.asarray(sq), np.asarray(sq_ref), rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(np.asarray(c), np.asarray(c_ref), rtol=1e-6)
    # gradients flow through the partitioned op's custom VJP too
    g = jax.grad(
        lambda d: sum(
            x.sum()
            for x in jax.jit(
                lambda dd: segment_sum_family(dd, seg_s, n, mask=mask_s, indices_are_sorted=True)
            )(d)[:2]
        )
    )(data_s)
    assert np.isfinite(np.asarray(g)).all()


def pytest_partitioned_family_inside_shard_map(monkeypatch):
    """Inside shard_map (the DP train step) operands are already local;
    the partitioned op must lower to the plain kernel per device."""
    from jax.sharding import Mesh, PartitionSpec as P

    from hydragnn_tpu.ops import segment_sum_family

    rng = np.random.default_rng(19)
    d_dev, e, h, n = 8, 256, 128, 40
    data = rng.normal(size=(d_dev, e, h)).astype(np.float32)
    seg = np.sort(rng.integers(0, n, (d_dev, e)), axis=1).astype(np.int32)

    mesh = Mesh(np.array(jax.devices()[:d_dev]), ("data",))

    monkeypatch.setenv("HYDRAGNN_PALLAS", "interpret")

    def local(d, i):
        s, sq, c = segment_sum_family(d[0], i[0], n, indices_are_sorted=True)
        return s[None]

    # check_vma=False matches every in-tree shard_map (sharded.py,
    # edge_sharded.py); interpret-mode pallas does not propagate vma
    fn = jax.jit(
        shard_map(
            local, mesh=mesh, in_specs=(P("data"), P("data")),
            out_specs=P("data"), check_vma=False,
        )
    )
    out = fn(jnp.asarray(data), jnp.asarray(seg))
    for i in range(d_dev):
        ref = jax.ops.segment_sum(jnp.asarray(data[i]), jnp.asarray(seg[i]), n)
        np.testing.assert_allclose(
            np.asarray(out[i]), np.asarray(ref), rtol=1e-4, atol=1e-4
        )


def pytest_xla_segment_ops_context_forces_fallback(monkeypatch):
    """xla_segment_ops() must force the XLA path at trace time — the
    programmatic gate for vmap contexts where custom_partitioning has no
    batching rule (ADVICE r02 medium)."""
    from hydragnn_tpu.ops import segment_sum_family
    from hydragnn_tpu.ops.segment_pallas import _use_pallas, xla_segment_ops

    rng = np.random.default_rng(23)
    b, e, h, n = 3, 200, 128, 30
    data = jnp.asarray(rng.normal(size=(b, e, h)).astype(np.float32))
    seg = jnp.asarray(np.sort(rng.integers(0, n, (b, e)), axis=1).astype(np.int32))

    monkeypatch.setenv("HYDRAGNN_PALLAS", "interpret")  # would pick the kernel...
    assert _use_pallas(data[0], True)
    with xla_segment_ops():
        assert not _use_pallas(data[0], True)  # ...but the context wins
        # vmap over the family op traces cleanly on the XLA path
        out = jax.vmap(
            lambda d, i: segment_sum_family(d, i, n, indices_are_sorted=True)[0]
        )(data, seg)
    for i in range(b):
        ref = jax.ops.segment_sum(data[i], seg[i], n)
        np.testing.assert_allclose(np.asarray(out[i]), np.asarray(ref), rtol=1e-5, atol=1e-5)


def pytest_family_float_weight_mask_gradient():
    """ADVICE r02: differentiating segment_sum_family with a FLOAT weight
    mask must (a) not raise, and (b) apply the weighted closed form
    (m*g_sum + 2*m^2*d*g_sumsq) — checked against autodiff of the
    mathematical definition. The mask itself is non-differentiable
    (stop_gradient contract)."""
    from hydragnn_tpu.ops import segment_sum_family

    rng = np.random.default_rng(29)
    e, h, n = 300, 8, 40
    data = jnp.asarray(rng.normal(size=(e, h)).astype(np.float32))
    seg = jnp.asarray(np.sort(rng.integers(0, n, e)).astype(np.int32))
    wmask = jnp.asarray(rng.random(e).astype(np.float32))

    def via_custom(d):
        s, sq, c = segment_sum_family(d, seg, n, mask=wmask, indices_are_sorted=True)
        return (s * 1.3).sum() + (sq * 0.7).sum()

    def via_autodiff(d):
        m = wmask[:, None]
        dm = d * m
        s = jax.ops.segment_sum(dm, seg, n)
        sq = jax.ops.segment_sum(dm * dm, seg, n)
        return (s * 1.3).sum() + (sq * 0.7).sum()

    np.testing.assert_allclose(float(via_custom(data)), float(via_autodiff(data)), rtol=1e-5)
    g_custom = jax.grad(via_custom)(data)
    g_auto = jax.grad(via_autodiff)(data)
    np.testing.assert_allclose(np.asarray(g_custom), np.asarray(g_auto), rtol=1e-4, atol=1e-5)

    # mask arg gets a zero cotangent, not an error
    g_mask = jax.grad(
        lambda m: segment_sum_family(data, seg, n, mask=m, indices_are_sorted=True)[0].sum()
    )(wmask)
    assert not np.asarray(g_mask).any()


def pytest_pallas_knob_1_requires_tpu_backend(monkeypatch):
    """ADVICE r02: HYDRAGNN_PALLAS=1 on a non-TPU backend must fall back
    to XLA instead of crashing at Mosaic lowering."""
    from hydragnn_tpu.ops.segment_pallas import _use_pallas

    data = jnp.zeros((16, 128), jnp.float32)
    monkeypatch.setenv("HYDRAGNN_PALLAS", "1")
    assert jax.default_backend() == "cpu"
    assert not _use_pallas(data, True)  # CPU: knob 1 falls back


def pytest_bcast_gather_matches_indexing():
    """CSR-broadcast row gather (sorted ids): kernel output must be
    bit-exact against plain indexing across chunk boundaries, window
    clamping near the table end, low- and high-degree id patterns, f32
    and bf16 tables."""
    from hydragnn_tpu.ops.segment_pallas import _bcast_kernel_call

    rng = np.random.default_rng(23)
    cases = [
        (700, 100, 128, "f32"),      # single-chunk tail
        (3000, 40, 128, "f32"),      # high degree, few rows (clamped windows)
        (2048, 2000, 128, "f32"),    # low degree ~1: chunk spans ~CE rows
        (1537, 77, 256, "bf16"),     # multi-chunk + ragged tail + wide H
    ]
    for e, n, h, dt in cases:
        ids = jnp.asarray(np.sort(rng.integers(0, n, e)).astype(np.int32))
        table = jnp.asarray(rng.normal(size=(n, h)).astype(np.float32))
        if dt == "bf16":
            table = table.astype(jnp.bfloat16)
        out = _bcast_kernel_call(table, ids, interpret=True)
        np.testing.assert_array_equal(np.asarray(out), np.asarray(table[ids]))


def pytest_bcast_gather_in_vjps_interpret(monkeypatch):
    """The family and extremum backward passes route their widening
    gathers through the CSR-broadcast kernel when ids are sorted: grads
    under HYDRAGNN_PALLAS=interpret must match HYDRAGNN_PALLAS=0."""
    from hydragnn_tpu.graph import segment as S
    from hydragnn_tpu.ops import segment_sum_family

    rng = np.random.default_rng(29)
    e, h, n = 900, 128, 120
    data = jnp.asarray(rng.normal(size=(e, h)).astype(np.float32))
    seg = jnp.asarray(np.sort(rng.integers(0, n, e)).astype(np.int32))
    mask = jnp.asarray(rng.random(e) > 0.2)

    def loss(d):
        s, sq, c = segment_sum_family(d, seg, n, mask=mask, indices_are_sorted=True)
        mx = S.segment_max(d, seg, n, mask=mask, indices_are_sorted=True)
        mn = S.segment_min(d, seg, n, mask=mask, indices_are_sorted=True)
        xr = S.gather_rows(jnp.tanh(s), seg, n, True)
        return (s * s).sum() + sq.sum() + (mx * mn).sum() + xr.sum()

    monkeypatch.setenv("HYDRAGNN_PALLAS", "0")
    g_xla = jax.jit(jax.grad(loss))(data)
    monkeypatch.setenv("HYDRAGNN_PALLAS", "interpret")
    g_k = jax.jit(jax.grad(loss))(data)
    np.testing.assert_allclose(
        np.asarray(g_k), np.asarray(g_xla), rtol=1e-5, atol=1e-5
    )


def pytest_bcast_gather_edge_sharded_mesh(monkeypatch):
    """The CSR-broadcast op's custom_partitioning rule: edge-sharded ids
    on the 8-device CPU mesh gather per-shard from a replicated table
    and match plain indexing."""
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from hydragnn_tpu.ops.segment_pallas import gather_rows_sorted_fast

    rng = np.random.default_rng(31)
    e, h, n = 1024, 128, 96
    ids = jnp.asarray(np.sort(rng.integers(0, n, e)).astype(np.int32))
    table = jnp.asarray(rng.normal(size=(n, h)).astype(np.float32))

    mesh = Mesh(np.array(jax.devices()[:8]), ("data",))
    ids_s = jax.device_put(ids, NamedSharding(mesh, P("data")))
    table_s = jax.device_put(table, NamedSharding(mesh, P()))

    monkeypatch.setenv("HYDRAGNN_PALLAS", "interpret")
    out = jax.jit(gather_rows_sorted_fast)(table_s, ids_s)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(table[ids]))


def _pna_reference(v, recv, n, mask):
    """Composed reference for pna_aggregate from the plain building
    blocks (the pre-fusion formulation)."""
    from hydragnn_tpu.graph import segment as S
    from hydragnn_tpu.ops import segment_sum_family

    s, sq, cnt = segment_sum_family(v, recv, n, mask=mask, indices_are_sorted=True)
    mx = S.segment_max(v, recv, n, mask=mask, indices_are_sorted=True)
    mn = S.segment_min(v, recv, n, mask=mask, indices_are_sorted=True)
    return s, sq, cnt, mx, mn


def pytest_pna_aggregate_matches_composed(monkeypatch):
    """pna_aggregate forward AND gradient must match the composed
    segment ops — f32/bf16, with/without mask, deliberate ties, both
    the unfused (HYDRAGNN_PALLAS=0) and kernel (interpret) backwards."""
    rng = np.random.default_rng(37)
    e, h, n = 1200, 128, 90
    recv = jnp.asarray(np.sort(rng.integers(0, n, e)).astype(np.int32))
    base = rng.normal(size=(e, h)).astype(np.float32)
    # deliberate ties: quantize so segments share extrema
    base = np.round(base * 4) / 4
    mask_b = jnp.asarray(rng.random(e) > 0.2)

    from hydragnn_tpu.ops import pna_aggregate

    for dtype in (jnp.float32, jnp.bfloat16):
        v0 = jnp.asarray(base).astype(dtype)
        for mask in (None, mask_b):
            def loss_f(v, agg):
                s, sq, cnt, both = agg(v)
                mx, mn = both[:, :h], -both[:, h:]
                return (
                    (s * s).sum() + sq.sum()
                    + (mx.astype(jnp.float32) * 2.0).sum()
                    + (mn.astype(jnp.float32) * 3.0).sum()
                )

            def agg_fused(v, _mask=mask):
                return pna_aggregate(v, recv, n, mask=_mask, indices_are_sorted=True)

            def agg_ref(v, _mask=mask):
                s, sq, cnt, mx, mn = _pna_reference(v, recv, n, _mask)
                return s, sq, cnt, jnp.concatenate([mx, -mn], axis=-1)

            for knob in ("0", "interpret"):
                monkeypatch.setenv("HYDRAGNN_PALLAS", knob)
                out_f = jax.jit(lambda v: agg_fused(v))(v0)
                monkeypatch.setenv("HYDRAGNN_PALLAS", "0")
                out_r = jax.jit(lambda v: agg_ref(v))(v0)
                np.testing.assert_allclose(
                    np.asarray(out_f[2]), np.asarray(out_r[2]), rtol=1e-6,
                    err_msg=f"cnt {dtype} mask={mask is not None} {knob}",
                )
                for a, b, name in zip(out_f[:2], out_r[:2], ("sum", "sumsq")):
                    np.testing.assert_allclose(
                        np.asarray(a), np.asarray(b), rtol=2e-2, atol=2e-2,
                        err_msg=f"{name} {dtype} mask={mask is not None} {knob}",
                    )
                np.testing.assert_array_equal(
                    np.asarray(out_f[3]), np.asarray(out_r[3]),
                    err_msg=f"both {dtype} mask={mask is not None} {knob}",
                )

                monkeypatch.setenv("HYDRAGNN_PALLAS", knob)
                g_f = jax.jit(jax.grad(lambda v: loss_f(v, agg_fused)))(v0)
                monkeypatch.setenv("HYDRAGNN_PALLAS", "0")
                g_r = jax.jit(jax.grad(lambda v: loss_f(v, agg_ref)))(v0)
                np.testing.assert_allclose(
                    np.asarray(g_f, np.float32), np.asarray(g_r, np.float32),
                    rtol=2e-2, atol=2e-2,
                    err_msg=f"grad {dtype} mask={mask is not None} {knob}",
                )


def pytest_pna_aggregate_narrow_width_lane_pads(monkeypatch):
    """conv_0-shaped narrow widths must lane-pad through the fused op
    (kernel backward in interpret mode) and match the unfused path."""
    rng = np.random.default_rng(41)
    e, h, n = 900, 24, 70
    recv = jnp.asarray(np.sort(rng.integers(0, n, e)).astype(np.int32))
    v0 = jnp.asarray(np.round(rng.normal(size=(e, h)) * 4) / 4, dtype=jnp.float32)
    mask = jnp.asarray(rng.random(e) > 0.25)

    from hydragnn_tpu.ops import pna_aggregate

    def loss(v):
        s, sq, cnt, both = pna_aggregate(v, recv, n, mask=mask, indices_are_sorted=True)
        return (s * s).sum() + sq.sum() + both.sum() * 2.0 + cnt.sum()

    monkeypatch.setenv("HYDRAGNN_PALLAS", "0")
    ref_out = jax.jit(lambda v: pna_aggregate(v, recv, n, mask=mask, indices_are_sorted=True))(v0)
    ref_g = jax.jit(jax.grad(loss))(v0)
    monkeypatch.setenv("HYDRAGNN_PALLAS", "interpret")
    k_out = jax.jit(lambda v: pna_aggregate(v, recv, n, mask=mask, indices_are_sorted=True))(v0)
    k_g = jax.jit(jax.grad(loss))(v0)
    for a, b in zip(k_out, ref_out):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(k_g), np.asarray(ref_g), rtol=1e-5, atol=1e-5)


def pytest_pna_aggregate_grad_inside_shard_map(monkeypatch):
    """pna_aggregate's fused backward must trace and match the XLA path
    under jax.shard_map (the DP train-step context). check_vma=False
    like every in-tree shard_map: interpret-mode pallas' internal grid
    indexing is not vma-aware (hlo_interpreter dynamic_slice), so
    check_vma=True only works with the compiled Mosaic kernels on a
    real TPU — where the K1/K2 out_shapes now declare their vma and
    operands are pvary-promoted like the sibling kernels."""
    from jax.sharding import Mesh, PartitionSpec as P

    from hydragnn_tpu.ops import pna_aggregate

    rng = np.random.default_rng(43)
    d_dev, e, h, n = 8, 512, 128, 40
    data = np.round(rng.normal(size=(d_dev, e, h)) * 4).astype(np.float32) / 4
    seg = np.sort(rng.integers(0, n, (d_dev, e)), axis=1).astype(np.int32)

    mesh = Mesh(np.array(jax.devices()[:d_dev]), ("data",))
    monkeypatch.setenv("HYDRAGNN_PALLAS", "interpret")

    def local_loss(d, i):
        s, sq, cnt, both = pna_aggregate(d[0], i[0], n, indices_are_sorted=True)
        return ((s * s).sum() + sq.sum() + both.sum())[None]

    def loss(d, i):
        per = shard_map(
            local_loss, mesh=mesh, in_specs=(P("data"), P("data")),
            out_specs=P("data"), check_vma=False,
        )(d, i)
        return per.sum()

    g = jax.jit(jax.grad(loss))(jnp.asarray(data), jnp.asarray(seg))

    monkeypatch.setenv("HYDRAGNN_PALLAS", "0")
    g_ref = jax.jit(jax.grad(loss))(jnp.asarray(data), jnp.asarray(seg))
    np.testing.assert_allclose(
        np.asarray(g), np.asarray(g_ref), rtol=1e-5, atol=1e-5
    )


def pytest_gather_presum_stats_matches_reference(monkeypatch):
    """Fused gather + K-group pre-reduction (r05): forward equals the
    unfused composition over a materialized gather, and the custom VJP
    (regather + differentiate the composition) matches plain AD of that
    composition — values AND grads, with deliberate mask structure."""
    from hydragnn_tpu.graph.batch import _block_windows
    from hydragnn_tpu.ops.segment_pallas import (
        _presum_stats_ref,
        gather_presum_eligible,
        gather_presum_stats,
    )

    monkeypatch.setenv("HYDRAGNN_PALLAS", "interpret")
    monkeypatch.setenv("HYDRAGNN_LOCAL_MIN_ROWS", "0")

    rng = np.random.default_rng(17)
    e, n_rows, h, K = 2048, 512, 128, 8
    # unsorted-but-local senders: confined to 64-node blocks like
    # batched-graph senders; round values so f32/bf16 compares tie
    table = np.round(rng.normal(size=(n_rows, h)) * 4).astype(np.float32) / 4
    grp = np.sort(rng.integers(0, 32, e))
    send = (grp * 16 + rng.integers(0, 16, e)).astype(np.int32)
    mask = rng.random(e) > 0.25
    # whole K-groups masked too (empty-group fill path)
    mask[64:72] = False
    perm = np.argsort(send, kind="stable").astype(np.int32)
    win = jnp.asarray(_block_windows(send, perm, n_rows))

    assert gather_presum_eligible(jnp.asarray(table), jnp.asarray(send), win, K)
    # indivisible chunk/K combos must FALL BACK, not crash at trace time
    assert not gather_presum_eligible(jnp.asarray(table), jnp.asarray(send), win, 3)

    def fused_loss(t):
        stats, both = gather_presum_stats(
            t, jnp.asarray(send), jnp.asarray(mask), win, n_rows, K
        )
        return (stats * stats).sum() + both.astype(jnp.float32).sum()

    def ref_loss(t):
        v = t[jnp.asarray(send)]
        stats, both = _presum_stats_ref(v, jnp.asarray(mask), K)
        return (stats * stats).sum() + both.astype(jnp.float32).sum()

    t = jnp.asarray(table)
    np.testing.assert_allclose(
        float(fused_loss(t)), float(ref_loss(t)), rtol=1e-5
    )
    g_fused = jax.jit(jax.grad(fused_loss))(t)
    g_ref = jax.jit(jax.grad(ref_loss))(t)
    np.testing.assert_allclose(
        np.asarray(g_fused), np.asarray(g_ref), rtol=1e-5, atol=1e-5
    )

    # bf16 table: forward values must agree with the bf16 composition
    tb = t.astype(jnp.bfloat16)
    s_f, b_f = gather_presum_stats(
        tb, jnp.asarray(send), jnp.asarray(mask), win, n_rows, K
    )
    s_r, b_r = _presum_stats_ref(tb[jnp.asarray(send)], jnp.asarray(mask), K)
    np.testing.assert_allclose(
        np.asarray(s_f), np.asarray(s_r), rtol=1e-6, atol=1e-6
    )
    np.testing.assert_array_equal(
        np.asarray(b_f.astype(jnp.float32)), np.asarray(b_r.astype(jnp.float32))
    )
