"""Test configuration: force an 8-device virtual CPU mesh.

Mirrors the reference's CI strategy of testing distributed behavior without
a cluster (reference: .github/workflows/CI.yml runs pytest serial + under
``mpirun -n 2``). Here multi-device paths are exercised on 8 virtual XLA
CPU devices so sharding/collective code compiles and runs in CI.

Must run before jax is imported anywhere.
"""

import os
import sys

os.environ.setdefault("JAX_ENABLE_X64", "0")
# Model-level introspection OFF for the suite (production default is
# ON): every tiny training test would otherwise compile the separate
# per-head diagnostics executable and lower the train step for the
# hardware ledger — measured ~2+ minutes across the suite's dozens of
# training runs, which blows the tier-1 time budget. The dedicated
# introspection tests (tests/test_introspect.py, the flight-record e2e
# in test_obs.py) and the ci.sh telemetry smoke opt back in explicitly.
os.environ.setdefault("HYDRAGNN_DIAGNOSTICS", "0")
# Persistent compilation cache: repeated test runs skip recompilation.
# Gated OFF on jax < 0.5: the 0.4.x persistent cache round-trips jitted
# executables without their input-output aliasing (donation) metadata, so
# a WARM cache hit returns a train step whose optimizer update never
# lands (probed: cold run passes, identical warm rerun fails; it can also
# abort outright). Correctness beats rerun speed there.
# importlib.metadata, not `import jax` — jax must not load before the
# platform pin below.
try:
    from importlib.metadata import version as _pkg_version

    _jax_major_minor = tuple(
        int(p) for p in _pkg_version("jax").split(".")[:2]
    )
except Exception:  # unknown/dev version string: assume current jax
    _jax_major_minor = (99, 0)
if _jax_major_minor >= (0, 5):
    os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/jax_test_cache")
    os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0.5")

# The ambient image registers a remote-TPU ("axon") PJRT plugin through
# sitecustomize and pre-sets JAX_PLATFORMS=axon; if that backend wins, test
# runs hang retrying the tunnel. pin_virtual_cpu_mesh pins the config
# itself, not just the env; require_ fails fast (instead of hanging) if
# some earlier-loaded plugin already initialized the backend. The platform
# helper is loaded by file path (via the jax-free __graft_entry__ loader)
# because importing it through the package would execute
# hydragnn_tpu/__init__, which imports jax before the pin.
sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), os.pardir))
from __graft_entry__ import _load_platform_module  # noqa: E402

_platform = _load_platform_module()
_platform.pin_virtual_cpu_mesh(8)
_platform.require_virtual_cpu_mesh(8)
