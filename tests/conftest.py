"""Test configuration: force an 8-device virtual CPU mesh.

Mirrors the reference's CI strategy of testing distributed behavior without
a cluster (reference: .github/workflows/CI.yml runs pytest serial + under
``mpirun -n 2``). Here multi-device paths are exercised on 8 virtual XLA
CPU devices so sharding/collective code compiles and runs in CI.

Must run before jax is imported anywhere.
"""

import os

# Unconditional: the ambient environment may pre-set JAX_PLATFORMS to the
# real TPU backend, and tests must run on the virtual CPU mesh.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")
# Persistent compilation cache: repeated test runs skip recompilation.
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/jax_test_cache")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0.5")

# The ambient image registers a remote-TPU ("axon") PJRT plugin through
# sitecustomize and pre-sets JAX_PLATFORMS=axon; if that backend wins, test
# runs hang retrying the tunnel. Pin the config itself, not just the env.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
