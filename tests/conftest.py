"""Test configuration: force an 8-device virtual CPU mesh.

Mirrors the reference's CI strategy of testing distributed behavior without
a cluster (reference: .github/workflows/CI.yml runs pytest serial + under
``mpirun -n 2``). Here multi-device paths are exercised on 8 virtual XLA
CPU devices so sharding/collective code compiles and runs in CI.

Must run before jax is imported anywhere.
"""

import os
import sys

os.environ.setdefault("JAX_ENABLE_X64", "0")
# Persistent compilation cache: repeated test runs skip recompilation.
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/jax_test_cache")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0.5")

# The ambient image registers a remote-TPU ("axon") PJRT plugin through
# sitecustomize and pre-sets JAX_PLATFORMS=axon; if that backend wins, test
# runs hang retrying the tunnel. pin_virtual_cpu_mesh pins the config
# itself, not just the env; require_ fails fast (instead of hanging) if
# some earlier-loaded plugin already initialized the backend. The platform
# helper is loaded by file path (via the jax-free __graft_entry__ loader)
# because importing it through the package would execute
# hydragnn_tpu/__init__, which imports jax before the pin.
sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), os.pardir))
from __graft_entry__ import _load_platform_module  # noqa: E402

_platform = _load_platform_module()
_platform.pin_virtual_cpu_mesh(8)
_platform.require_virtual_cpu_mesh(8)
