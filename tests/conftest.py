"""Test configuration: force an 8-device virtual CPU mesh.

Mirrors the reference's CI strategy of testing distributed behavior without
a cluster (reference: .github/workflows/CI.yml runs pytest serial + under
``mpirun -n 2``). Here multi-device paths are exercised on 8 virtual XLA
CPU devices so sharding/collective code compiles and runs in CI.

Must run before jax is imported anywhere.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")
