"""graftlint: per-rule true-positive / near-miss fixtures, suppression
and baseline machinery, --changed plumbing, artifact validation, the
knob registry, and the meta-test that the shipped tree is lint-clean.

Fixtures are written to tmp_path (outside the repo) so per-rule path
policies (tests/ exemptions etc.) don't mask them, and every run_lint
call builds a fresh rule set — the HG005/HG006 rules carry per-run
state loaded from the real obs/flight.py and utils/knobs.py tables.
"""

import importlib.util
import json
import os
import subprocess
import textwrap

import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_cli():
    path = os.path.join(REPO_ROOT, "tools", "graftlint.py")
    spec = importlib.util.spec_from_file_location("_graftlint_cli", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


CLI = _load_cli()
CORE, RULES, ARTIFACTS = CLI._load_lint_pkg()

BASELINE = os.path.join(REPO_ROOT, "tools", "graftlint_baseline.json")


def lint(tmp_path, source, rule_ids=None, name="fixture.py"):
    """Write ``source`` to a tmp file and lint it with fresh rules."""
    p = tmp_path / name
    p.write_text(textwrap.dedent(source))
    rules = RULES.all_rules(REPO_ROOT)
    if rule_ids:
        rules = [r for r in rules if r.id in set(rule_ids)]
    return CORE.run_lint(REPO_ROOT, rules, paths=[str(p)])


# ---------------------------------------------------------------- HG001


class TestHostSyncInHotPath:
    def test_flags_sync_in_traced_body(self, tmp_path):
        findings = lint(
            tmp_path,
            """
            def make_train_step(model):
                def step(state, batch):
                    return float(state.loss)

                return step
            """,
            ["HG001"],
        )
        assert [f.rule for f in findings] == ["HG001"]
        assert "make_train_step" in findings[0].message

    def test_flags_sync_reachable_via_helper(self, tmp_path):
        findings = lint(
            tmp_path,
            """
            def _build_body(model):
                def body(state):
                    state.loss.block_until_ready()
                    return state

                return body


            def make_scan_epoch(model):
                return _build_body(model)
            """,
            ["HG001"],
        )
        assert [f.rule for f in findings] == ["HG001"]

    def test_builder_level_sync_is_build_time(self, tmp_path):
        # host ops directly in the builder run once at build time: fine
        findings = lint(
            tmp_path,
            """
            def make_train_step(model):
                width = int(model.width)

                def step(state, batch):
                    return state

                return step
            """,
            ["HG001"],
        )
        assert findings == []

    def test_non_hot_builder_ignored(self, tmp_path):
        findings = lint(
            tmp_path,
            """
            def make_report(model):
                def fmt(state):
                    return float(state.loss)

                return fmt
            """,
            ["HG001"],
        )
        assert findings == []


# ---------------------------------------------------------------- HG002


class TestMeshOutsidePartitioner:
    def test_flags_aliased_import_and_call(self, tmp_path):
        # the exact case the old grep gate could not see
        findings = lint(
            tmp_path,
            """
            from jax.sharding import Mesh as M


            def build(devices):
                return M(devices, ("data",))
            """,
            ["HG002"],
        )
        assert len(findings) == 2  # the import and the construction
        assert all(f.rule == "HG002" for f in findings)

    def test_flags_module_alias_attribute_call(self, tmp_path):
        findings = lint(
            tmp_path,
            """
            import jax.sharding as sh


            def build(devices):
                return sh.Mesh(devices, ("data",))
            """,
            ["HG002"],
        )
        assert [f.rule for f in findings] == ["HG002"]

    def test_partitioner_usage_is_clean(self, tmp_path):
        findings = lint(
            tmp_path,
            """
            from hydragnn_tpu.parallel import Partitioner


            def build(devices):
                part = Partitioner(devices)
                return part.mesh, part.mesh_shape()
            """,
            ["HG002"],
        )
        assert findings == []


# ---------------------------------------------------------------- HG003


class TestDonationAfterDeserialize:
    def test_flags_direct_deserialize(self, tmp_path):
        findings = lint(
            tmp_path,
            """
            from jax import export


            def load(payload):
                return export.deserialize_and_load(payload)
            """,
            ["HG003"],
        )
        assert [f.rule for f in findings] == ["HG003"]
        assert "ExecCache.load" in findings[0].message

    def test_cache_api_is_clean(self, tmp_path):
        findings = lint(
            tmp_path,
            """
            def load(cache, key):
                return cache.load(key)  # the gated path


            def parse(blob):
                return deserialize_config(blob)  # not an executable loader
            """,
            ["HG003"],
        )
        assert findings == []


# ---------------------------------------------------------------- HG004


class TestJitInLoop:
    def test_flags_jit_under_loop(self, tmp_path):
        findings = lint(
            tmp_path,
            """
            import jax


            def run(fns, x):
                out = []
                for fn in fns:
                    out.append(jax.jit(fn)(x))
                return out
            """,
            ["HG004"],
        )
        assert [f.rule for f in findings] == ["HG004"]
        # promoted warning -> error (ISSUE 13): a recompile-per-iteration
        # hazard on the hot path fails CI outright
        assert findings[0].severity == "error"

    def test_hoisted_jit_is_clean(self, tmp_path):
        findings = lint(
            tmp_path,
            """
            import jax


            def run(fn, xs):
                compiled = jax.jit(fn)
                out = []
                for x in xs:
                    out.append(compiled(x))
                return out
            """,
            ["HG004"],
        )
        assert findings == []


# ---------------------------------------------------------------- HG005


class TestUnregisteredFlightKind:
    def test_flags_unknown_kind(self, tmp_path):
        findings = lint(
            tmp_path,
            """
            def emit(flight):
                flight.record("totally_bogus_kind", x=1)
            """,
            ["HG005"],
        )
        assert [f.rule for f in findings] == ["HG005"]
        assert "totally_bogus_kind" in findings[0].message

    def test_registered_and_dynamic_kinds_are_clean(self, tmp_path):
        kinds = CORE.load_flight_kinds(REPO_ROOT)
        assert "run_start" in kinds and "error" in kinds
        findings = lint(
            tmp_path,
            """
            def emit(flight, kind):
                flight.record("run_start", manifest={})
                flight.record("error", error="e", error_type="E")
                flight.record(kind, x=1)  # non-literal: can't judge, stay quiet
            """,
            ["HG005"],
        )
        assert findings == []


# ---------------------------------------------------------------- HG006


class TestUndeclaredEnvKnob:
    def test_flags_rogue_knob(self, tmp_path):
        findings = lint(
            tmp_path,
            """
            import os


            def read():
                return os.environ.get("HYDRAGNN_DEFINITELY_NOT_A_KNOB")
            """,
            ["HG006"],
        )
        assert [f.rule for f in findings] == ["HG006"]
        assert "HYDRAGNN_DEFINITELY_NOT_A_KNOB" in findings[0].message

    def test_registered_name_and_family_prefix_are_clean(self, tmp_path):
        registry = CORE.load_knob_registry(REPO_ROOT)
        assert "HYDRAGNN_TELEMETRY" in registry
        assert any(k.startswith("HYDRAGNN_INJECT_") for k in registry)
        findings = lint(
            tmp_path,
            """
            import os


            def read(env):
                a = os.environ.get("HYDRAGNN_TELEMETRY")
                fam = [k for k in env if k.startswith("HYDRAGNN_INJECT_")]
                return a, fam
            """,
            ["HG006"],
        )
        assert findings == []

    def test_stale_registry_arm_full_tree_only(self, tmp_path):
        rule = RULES.UndeclaredEnvKnob(REPO_ROOT)
        # nothing referenced: on a full-tree scan every knob looks stale
        stale = list(rule.finalize())
        assert stale and all(f.rule == "HG006" for f in stale)
        assert all(f.path.endswith("utils/knobs.py") for f in stale)
        # but run_lint only calls finalize on full-tree scans
        p = tmp_path / "empty.py"
        p.write_text("x = 1\n")
        findings = CORE.run_lint(
            REPO_ROOT, [RULES.UndeclaredEnvKnob(REPO_ROOT)], paths=[str(p)]
        )
        assert findings == []


# ---------------------------------------------------------------- HG007


class TestBareAssertContract:
    def test_flags_assert(self, tmp_path):
        findings = lint(
            tmp_path,
            """
            def check(batch):
                assert batch.n_node.ndim == 1
                return batch
            """,
            ["HG007"],
        )
        assert [f.rule for f in findings] == ["HG007"]

    def test_raise_is_clean(self, tmp_path):
        findings = lint(
            tmp_path,
            """
            def check(batch):
                if batch.n_node.ndim != 1:
                    raise ValueError("n_node must be 1-D")
                return batch
            """,
            ["HG007"],
        )
        assert findings == []


# ---------------------------------------------------------------- HG008


class TestTracerLeak:
    def test_flags_self_store_in_jitted_body(self, tmp_path):
        findings = lint(
            tmp_path,
            """
            import jax


            class Model:
                @jax.jit
                def forward(self, x):
                    self.last = x
                    return x
            """,
            ["HG008"],
        )
        assert [f.rule for f in findings] == ["HG008"]
        assert "self.last" in findings[0].message

    def test_flags_global_in_function_passed_to_jit(self, tmp_path):
        findings = lint(
            tmp_path,
            """
            import jax

            _COUNT = 0


            def step(x):
                global _COUNT
                _COUNT = _COUNT + 1
                return x


            compiled = jax.jit(step)
            """,
            ["HG008"],
        )
        assert [f.rule for f in findings] == ["HG008"]

    def test_unjitted_method_is_clean(self, tmp_path):
        findings = lint(
            tmp_path,
            """
            class Model:
                def remember(self, x):
                    self.last = x  # eager method: storing is fine
                    return x
            """,
            ["HG008"],
        )
        assert findings == []


# ------------------------------------------------------- suppressions


class TestSuppressions:
    SRC = """
    def check(batch):
        assert batch.ok{comment}
        return batch
    """

    def test_same_line_suppression(self, tmp_path):
        src = self.SRC.format(
            comment="  # graftlint: disable=HG007 -- test fixture"
        )
        assert lint(tmp_path, src, ["HG007"]) == []

    def test_line_above_suppression(self, tmp_path):
        src = (
            "def check(batch):\n"
            "    # graftlint: disable=HG007 -- test fixture\n"
            "    assert batch.ok\n"
            "    return batch\n"
        )
        assert lint(tmp_path, src, ["HG007"]) == []

    def test_file_suppression(self, tmp_path):
        src = (
            "# graftlint: disable-file=HG007\n"
            "def check(batch):\n"
            "    assert batch.ok\n"
            "    return batch\n"
        )
        assert lint(tmp_path, src, ["HG007"]) == []

    def test_wrong_rule_suppression_does_not_mask(self, tmp_path):
        src = self.SRC.format(comment="  # graftlint: disable=HG001")
        findings = lint(tmp_path, src, ["HG007"])
        assert [f.rule for f in findings] == ["HG007"]


# ------------------------------------------------------------ baseline


class TestBaseline:
    def test_round_trip_silences_grandfathered_findings(self, tmp_path):
        fixture = tmp_path / "legacy.py"
        fixture.write_text("def check(x):\n    assert x\n    return x\n")
        rules = [RULES.BareAssertContract()]
        findings = CORE.run_lint(REPO_ROOT, rules, paths=[str(fixture)])
        assert len(findings) == 1

        baseline = tmp_path / "baseline.json"
        CORE.write_baseline(str(baseline), findings)
        again = CORE.run_lint(
            REPO_ROOT,
            [RULES.BareAssertContract()],
            paths=[str(fixture)],
            baseline=str(baseline),
        )
        assert again == []

        # a NEW finding in the same file still surfaces
        fixture.write_text(
            "def check(x):\n    assert x\n    return x\n"
            "def other(y):\n    assert y != 0\n    return y\n"
        )
        fresh = CORE.run_lint(
            REPO_ROOT,
            [RULES.BareAssertContract()],
            paths=[str(fixture)],
            baseline=str(baseline),
        )
        assert len(fresh) == 1 and "y != 0" in fresh[0].snippet

    def test_fingerprint_survives_line_churn(self, tmp_path):
        fixture = tmp_path / "churn.py"
        fixture.write_text("def check(x):\n    assert x\n")
        (f1,) = CORE.run_lint(
            REPO_ROOT, [RULES.BareAssertContract()], paths=[str(fixture)]
        )
        fixture.write_text("import os\n\n\ndef check(x):\n    assert x\n")
        (f2,) = CORE.run_lint(
            REPO_ROOT, [RULES.BareAssertContract()], paths=[str(fixture)]
        )
        assert f1.line != f2.line
        assert f1.fingerprint() == f2.fingerprint()

    def test_committed_baseline_is_empty(self):
        with open(BASELINE) as f:
            data = json.load(f)
        assert data["findings"] == []


# ----------------------------------------------------------- --changed


class TestChangedMode:
    def _git(self, repo, *args):
        subprocess.run(
            ["git", "-c", "user.email=t@t", "-c", "user.name=t",
             "-C", str(repo)] + list(args),
            check=True,
            capture_output=True,
        )

    def test_changed_paths_tracks_modified_and_untracked(self, tmp_path):
        self._git(tmp_path, "init", "-q")
        mod = tmp_path / "mod.py"
        mod.write_text("def ok(x):\n    return x\n")
        self._git(tmp_path, "add", ".")
        self._git(tmp_path, "commit", "-q", "-m", "seed")
        assert CORE.changed_paths(str(tmp_path)) == []

        mod.write_text("def ok(x):\n    assert x\n    return x\n")
        (tmp_path / "new.py").write_text("def n(y):\n    assert y\n")
        changed = CORE.changed_paths(str(tmp_path))
        assert changed == ["mod.py", "new.py"]

        findings = CORE.run_lint(
            str(tmp_path), [RULES.BareAssertContract()], paths=changed
        )
        assert sorted(f.path for f in findings) == ["mod.py", "new.py"]


# ----------------------------------------------------------- artifacts


class TestArtifacts:
    def test_committed_artifacts_are_valid(self):
        assert ARTIFACTS.validate_artifacts(REPO_ROOT) == []

    def test_unregistered_kind_is_reported(self, tmp_path):
        art = tmp_path / "bogus.jsonl"
        art.write_text(
            json.dumps(
                {"v": 2, "kind": "totally_bogus_kind", "t": 0.0, "rank": 0}
            )
            + "\n"
        )
        findings = ARTIFACTS.validate_artifacts(REPO_ROOT, [str(art)])
        assert any("totally_bogus_kind" in f.message for f in findings)

    def test_missing_required_field_is_reported(self, tmp_path):
        art = tmp_path / "short.jsonl"
        art.write_text(
            json.dumps({"v": 2, "kind": "compile", "t": 0.0, "rank": 0})
            + "\n"
        )  # "compile" requires "count"
        findings = ARTIFACTS.validate_artifacts(REPO_ROOT, [str(art)])
        assert any(
            "compile" in f.message and "count" in f.message for f in findings
        )

    def test_missing_file_is_reported(self, tmp_path):
        findings = ARTIFACTS.validate_artifacts(
            REPO_ROOT, [str(tmp_path / "nope.jsonl")]
        )
        assert [f.message for f in findings] == ["flight artifact missing"]

    def test_failed_bench_attempt_must_be_structured(self, tmp_path):
        # rc != 0 with only a raw traceback tail is NOT a valid failed
        # run record — it must carry status/retries/failure
        art = tmp_path / "BENCH_r99.json"
        bare = {"n": 9, "cmd": "python bench.py", "rc": 1, "tail": "boom",
                "parsed": None}
        art.write_text(json.dumps(bare))
        findings = ARTIFACTS.validate_artifacts(REPO_ROOT, [str(art)])
        msgs = " ".join(f.message for f in findings)
        assert "status" in msgs and "retries" in msgs and "failure" in msgs
        structured = dict(
            bare,
            status="failed",
            retries=2,
            failure={"stage": "backend_init", "error_type": "RuntimeError",
                     "error": "UNAVAILABLE"},
        )
        art.write_text(json.dumps(structured))
        assert ARTIFACTS.validate_artifacts(REPO_ROOT, [str(art)]) == []
        # a wrong status string on a failed attempt is a finding too
        art.write_text(json.dumps(dict(structured, status="ok")))
        findings = ARTIFACTS.validate_artifacts(REPO_ROOT, [str(art)])
        assert any("expected 'failed'" in f.message for f in findings)

    def test_fleet_record_requires_every_chaos_scenario(self, tmp_path):
        art = tmp_path / "BENCH_FLEET.json"
        record = {
            "metric": "fleet_sustained_qps", "value": 100.0,
            "unit": "graphs/sec", "replicas": 2, "qps_n1": 60.0,
            "qps_n2": 100.0, "scaleout_efficiency": 0.83,
            "warm_replica_aot_compiles": 0, "lost_futures": 0,
            "slo_p99_ms": 3000.0, "failures": [],
            "scenarios": {
                name: {"qps": 1.0}
                for name in ARTIFACTS._FLEET_SCENARIOS
            },
        }
        art.write_text(json.dumps(record))
        assert ARTIFACTS.validate_artifacts(REPO_ROOT, [str(art)]) == []
        del record["scenarios"]["replica_kill"]
        art.write_text(json.dumps(record))
        findings = ARTIFACTS.validate_artifacts(REPO_ROOT, [str(art)])
        assert any("replica_kill" in f.message for f in findings)


# ----------------------------------------------------------------- CLI


class TestCli:
    def test_strict_fixture_fails_with_json_artifact(self, tmp_path):
        fixture = tmp_path / "bad.py"
        fixture.write_text("def check(x):\n    assert x\n")
        out = tmp_path / "findings.json"
        rc = CLI.main(
            [str(fixture), "--rule", "HG007", "--strict", "--no-baseline",
             "--json", str(out)]
        )
        assert rc == 1
        payload = json.loads(out.read_text())
        assert payload["count"] == 1
        assert payload["findings"][0]["rule"] == "HG007"

    def test_unknown_rule_is_usage_error(self):
        assert CLI.main(["--rule", "HG999"]) == 2

    def test_list_rules(self, capsys):
        assert CLI.main(["--list-rules"]) == 0
        listed = capsys.readouterr().out
        for rid in ("HG001", "HG008"):
            assert rid in listed

    def test_promoted_hg004_fails_without_strict(self, tmp_path):
        # HG004 was promoted warning -> error (ISSUE 13): a jit built per
        # loop iteration now fails CI with or without --strict
        fixture = tmp_path / "warn.py"
        fixture.write_text(
            "import jax\n\n\ndef run(fns, x):\n"
            "    out = []\n"
            "    for f in fns:\n"
            "        out.append(jax.jit(f)(x))\n"
            "    return out\n"
        )
        rc = CLI.main([str(fixture), "--rule", "HG004", "--no-baseline"])
        assert rc == 1
        rc = CLI.main(
            [str(fixture), "--rule", "HG004", "--no-baseline", "--strict"]
        )
        assert rc == 1


# ------------------------------------------------------- knob registry


class TestKnobRegistry:
    def test_docs_match_registry(self):
        from hydragnn_tpu.utils import knobs

        with open(os.path.join(REPO_ROOT, "docs", "KNOBS.md")) as f:
            committed = f.read()
        assert committed == knobs.generate_docs(), (
            "docs/KNOBS.md is stale — regenerate with "
            "`python -m hydragnn_tpu.utils.knobs --write docs/KNOBS.md`"
        )

    def test_accessors_and_undeclared_error(self, monkeypatch):
        from hydragnn_tpu.utils import knobs

        monkeypatch.setenv("HYDRAGNN_RESIDENCY_VMEM_MB", "7.5")
        assert knobs.get_float("HYDRAGNN_RESIDENCY_VMEM_MB", 12.0) == 7.5
        monkeypatch.delenv("HYDRAGNN_RESIDENCY_VMEM_MB", raising=False)
        assert knobs.get_float("HYDRAGNN_RESIDENCY_VMEM_MB", 12.0) == 12.0
        monkeypatch.setenv("HYDRAGNN_TELEMETRY", "0")
        assert knobs.get_bool("HYDRAGNN_TELEMETRY", True) is False
        with pytest.raises(knobs.UndeclaredKnobError):
            knobs.raw("HYDRAGNN_DEFINITELY_NOT_A_KNOB")

    def test_active_injections_serve_filter(self, monkeypatch):
        from hydragnn_tpu.utils import knobs

        monkeypatch.setenv("HYDRAGNN_INJECT_NAN_STEP", "5")
        monkeypatch.setenv("HYDRAGNN_INJECT_SERVE_RAISE", "3")
        both = knobs.active_injections()
        assert "HYDRAGNN_INJECT_NAN_STEP" in both
        assert "HYDRAGNN_INJECT_SERVE_RAISE" in both
        train_only = knobs.active_injections(include_serve=False)
        assert "HYDRAGNN_INJECT_NAN_STEP" in train_only
        assert "HYDRAGNN_INJECT_SERVE_RAISE" not in train_only


# ------------------------------------------------------------ meta-test


class TestShippedTree:
    def test_tree_is_lint_clean_with_committed_baseline(self):
        findings = CORE.run_lint(
            REPO_ROOT, RULES.all_rules(REPO_ROOT), baseline=BASELINE
        )
        assert findings == [], "\n" + "\n".join(f.render() for f in findings)
