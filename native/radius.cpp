// Cell-list radius-graph pair finder — the native stand-in for
// torch-cluster's RadiusGraph / ase.neighborlist (SURVEY.md §2.9).
//
// rg_pairs(): all (src, dst) pairs with |src_pos[s] - dst_pos[t]| <= r,
// found via a uniform grid of cell size r (each dst point only scans the
// 27 surrounding cells), parallelized over dst points. The bipartite
// form serves both the plain radius graph (src == dst) and the periodic
// one (src = dst + image shift, one call per shift).
//
// Output protocol: writes up to `capacity` edges into the caller's
// buffers and returns the total pair count; when the total exceeds
// capacity the caller re-invokes with a larger buffer (the count is
// exact either way).

#include <atomic>
#include <cmath>
#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

namespace {

struct Grid {
  double origin[3];
  double inv_cell;
  int64_t dims[3];
  // CSR buckets over src points
  std::vector<int64_t> bucket_start;
  std::vector<int64_t> order;
};

inline int64_t clampi(int64_t v, int64_t lo, int64_t hi) {
  return v < lo ? lo : (v > hi ? hi : v);
}

// Returns false when the dense grid would be pathologically large
// (sparse point cloud / outlier coordinates) — the caller then reports
// "unsupported" and Python uses its sparse-key fallback instead of this
// allocation aborting the process.
bool build_grid(const double* src, int64_t n_src, const double* dst,
                int64_t n_dst, double r, Grid& g) {
  for (int d = 0; d < 3; ++d) {
    double mn = 1e300;
    for (int64_t i = 0; i < n_src; ++i) mn = std::min(mn, src[3 * i + d]);
    for (int64_t i = 0; i < n_dst; ++i) mn = std::min(mn, dst[3 * i + d]);
    g.origin[d] = mn;
  }
  g.inv_cell = 1.0 / std::max(r, 1e-12);
  int64_t mx[3] = {0, 0, 0};
  auto cell_coord = [&](const double* p, int d) {
    return (int64_t)std::floor((p[d] - g.origin[d]) * g.inv_cell);
  };
  for (int64_t i = 0; i < n_src; ++i)
    for (int d = 0; d < 3; ++d)
      mx[d] = std::max(mx[d], cell_coord(src + 3 * i, d));
  for (int64_t i = 0; i < n_dst; ++i)
    for (int d = 0; d < 3; ++d)
      mx[d] = std::max(mx[d], cell_coord(dst + 3 * i, d));
  for (int d = 0; d < 3; ++d) g.dims[d] = mx[d] + 1;

  // cap grid memory at ~8 cells per source point (plus slack): beyond
  // that the dense grid loses to the sparse fallback anyway
  const double cells_f =
      (double)g.dims[0] * (double)g.dims[1] * (double)g.dims[2];
  if (cells_f > 8.0 * (double)n_src + 65536.0) return false;

  const int64_t n_cells = g.dims[0] * g.dims[1] * g.dims[2];
  g.bucket_start.assign(n_cells + 1, 0);
  std::vector<int64_t> cell_id(n_src);
  for (int64_t i = 0; i < n_src; ++i) {
    int64_t k0 = cell_coord(src + 3 * i, 0);
    int64_t k1 = cell_coord(src + 3 * i, 1);
    int64_t k2 = cell_coord(src + 3 * i, 2);
    cell_id[i] = (k0 * g.dims[1] + k1) * g.dims[2] + k2;
    g.bucket_start[cell_id[i] + 1]++;
  }
  for (int64_t c = 0; c < n_cells; ++c) g.bucket_start[c + 1] += g.bucket_start[c];
  g.order.resize(n_src);
  std::vector<int64_t> cursor(g.bucket_start.begin(), g.bucket_start.end() - 1);
  for (int64_t i = 0; i < n_src; ++i) g.order[cursor[cell_id[i]]++] = i;
  return true;
}

struct Hit {
  int64_t s, t;
  double d;
};

}  // namespace

extern "C" {

// Returns the exact pair count and fills at most `capacity` entries of
// (senders, receivers, dists); returns -1 when the point distribution is
// unsuited to a dense grid (caller should use its fallback path).
int64_t rg_pairs(const double* src_pos, int64_t n_src, const double* dst_pos,
                 int64_t n_dst, double r, int64_t* senders, int64_t* receivers,
                 double* dists, int64_t capacity, int n_threads) {
  if (n_src == 0 || n_dst == 0) return 0;
  Grid g;
  if (!build_grid(src_pos, n_src, dst_pos, n_dst, r, g)) return -1;
  const double r2 = r * r;

  int T = n_threads > 0 ? n_threads
                        : (int)std::min<int64_t>(
                              std::max(1u, std::thread::hardware_concurrency()),
                              std::max<int64_t>(1, n_dst / 512));
  if (T < 1) T = 1;
  std::vector<std::vector<Hit>> results((size_t)T);

  auto worker = [&](int tid) {
    std::vector<Hit>& out = results[(size_t)tid];
    const int64_t lo = n_dst * tid / T, hi = n_dst * (tid + 1) / T;
    for (int64_t t = lo; t < hi; ++t) {
      const double* p = dst_pos + 3 * t;
      int64_t c0 = (int64_t)std::floor((p[0] - g.origin[0]) * g.inv_cell);
      int64_t c1 = (int64_t)std::floor((p[1] - g.origin[1]) * g.inv_cell);
      int64_t c2 = (int64_t)std::floor((p[2] - g.origin[2]) * g.inv_cell);
      for (int64_t a = clampi(c0 - 1, 0, g.dims[0] - 1);
           a <= clampi(c0 + 1, 0, g.dims[0] - 1); ++a)
        for (int64_t b = clampi(c1 - 1, 0, g.dims[1] - 1);
             b <= clampi(c1 + 1, 0, g.dims[1] - 1); ++b)
          for (int64_t c = clampi(c2 - 1, 0, g.dims[2] - 1);
               c <= clampi(c2 + 1, 0, g.dims[2] - 1); ++c) {
            const int64_t cell = (a * g.dims[1] + b) * g.dims[2] + c;
            for (int64_t k = g.bucket_start[cell]; k < g.bucket_start[cell + 1];
                 ++k) {
              const int64_t s = g.order[k];
              const double* q = src_pos + 3 * s;
              const double dx = q[0] - p[0], dy = q[1] - p[1], dz = q[2] - p[2];
              const double d2 = dx * dx + dy * dy + dz * dz;
              if (d2 <= r2) out.push_back({s, t, std::sqrt(d2)});
            }
          }
    }
  };

  if (T == 1) {
    worker(0);
  } else {
    std::vector<std::thread> threads;
    threads.reserve((size_t)T);
    for (int tid = 0; tid < T; ++tid) threads.emplace_back(worker, tid);
    for (auto& th : threads) th.join();
  }

  int64_t total = 0;
  for (auto& v : results) total += (int64_t)v.size();
  if (total <= capacity) {
    int64_t w = 0;
    for (auto& v : results)
      for (const Hit& h : v) {
        senders[w] = h.s;
        receivers[w] = h.t;
        dists[w] = h.d;
        ++w;
      }
  }
  return total;
}

}  // extern "C"
