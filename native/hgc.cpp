// hgc.cpp — native core of the HGC sharded binary graph container.
//
// TPU-native replacement for the ADIOS2 C++ engine the reference relies on
// (reference: hydragnn/utils/adiosdataset.py uses adios2 for parallel
// self-describing files with ragged-offset indexing; the native library
// itself lives outside the reference tree — SURVEY.md §2.9).
//
// Scope of the native layer: the READ hot path and node-local sharing.
//   - mmap-backed zero-copy field access with madvise hints,
//   - multi-threaded batched row-gather (sample slices -> packed batch
//     buffer), the operation the input pipeline runs per training batch,
//   - one-copy node-local /dev/shm preload so N processes on a host read
//     a parallel filesystem once (the AdiosDataset "shmem" mode,
//     reference adiosdataset.py:266-314).
// Schema/orchestration (meta.json, offsets, dtypes) stays in Python.
//
// Build: g++ -O3 -shared -fPIC -std=c++17 -pthread hgc.cpp -o libhgc.so

#include <atomic>
#include <cerrno>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <thread>
#include <vector>

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

extern "C" {

// Memory-map a file read-only. Returns base pointer or nullptr; size via
// *size_out. The mapping is MAP_SHARED so page-cache pages are shared
// across all processes on the host that map the same file.
void* hgc_mmap(const char* path, int64_t* size_out) {
  int fd = open(path, O_RDONLY);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0) {
    close(fd);
    return nullptr;
  }
  *size_out = static_cast<int64_t>(st.st_size);
  if (st.st_size == 0) {
    close(fd);
    return nullptr;
  }
  void* base = mmap(nullptr, st.st_size, PROT_READ, MAP_SHARED, fd, 0);
  close(fd);  // mapping persists after close
  if (base == MAP_FAILED) return nullptr;
  madvise(base, st.st_size, MADV_WILLNEED);
  return base;
}

void hgc_munmap(void* base, int64_t size) {
  if (base != nullptr && size > 0) munmap(base, size);
}

// Batched ragged row-gather: for each of n requests, copy cnt[k] rows of
// row_bytes starting at source row src_off[k] into the output at row
// out_off[k]. Parallelized over requests with a simple thread pool sized
// n_threads (<=0 -> hardware_concurrency, capped at 16).
void hgc_gather(const void* base, int64_t row_bytes, const int64_t* src_off,
                const int64_t* cnt, const int64_t* out_off, int64_t n,
                void* out, int n_threads) {
  if (n <= 0 || row_bytes <= 0) return;
  int hw = static_cast<int>(std::thread::hardware_concurrency());
  if (hw <= 0) hw = 4;
  int workers = n_threads > 0 ? n_threads : (hw > 16 ? 16 : hw);
  if (workers > n) workers = static_cast<int>(n);

  const char* src = static_cast<const char*>(base);
  char* dst = static_cast<char*>(out);

  if (workers <= 1) {
    for (int64_t k = 0; k < n; ++k) {
      memcpy(dst + out_off[k] * row_bytes, src + src_off[k] * row_bytes,
             static_cast<size_t>(cnt[k]) * row_bytes);
    }
    return;
  }

  std::atomic<int64_t> next(0);
  auto work = [&]() {
    for (;;) {
      int64_t k = next.fetch_add(1, std::memory_order_relaxed);
      if (k >= n) break;
      memcpy(dst + out_off[k] * row_bytes, src + src_off[k] * row_bytes,
             static_cast<size_t>(cnt[k]) * row_bytes);
    }
  };
  std::vector<std::thread> threads;
  threads.reserve(workers);
  for (int t = 0; t < workers; ++t) threads.emplace_back(work);
  for (auto& th : threads) th.join();
}

// Copy a file to a destination (used for one-copy /dev/shm preload).
// Returns 0 on success. The caller coordinates "first process copies,
// peers wait" (done in Python with an atomic rename).
int hgc_copy_file(const char* src_path, const char* dst_path) {
  int sfd = open(src_path, O_RDONLY);
  if (sfd < 0) return -1;
  struct stat st;
  if (fstat(sfd, &st) != 0) {
    close(sfd);
    return -1;
  }
  int dfd = open(dst_path, O_WRONLY | O_CREAT | O_TRUNC, 0644);
  if (dfd < 0) {
    close(sfd);
    return -1;
  }
  const size_t kChunk = 64u << 20;  // 64 MiB
  std::vector<char> buf(kChunk);
  int64_t remaining = st.st_size;
  while (remaining > 0) {
    size_t want = remaining < static_cast<int64_t>(kChunk)
                      ? static_cast<size_t>(remaining)
                      : kChunk;
    ssize_t got = read(sfd, buf.data(), want);
    if (got <= 0) {
      close(sfd);
      close(dfd);
      return -1;
    }
    ssize_t put = 0;
    while (put < got) {
      ssize_t w = write(dfd, buf.data() + put, got - put);
      if (w <= 0) {
        close(sfd);
        close(dfd);
        return -1;
      }
      put += w;
    }
    remaining -= got;
  }
  close(sfd);
  close(dfd);
  return 0;
}

}  // extern "C"
