"""QM9 example: molecular free-energy regression (graph head).

Mirrors the reference driver (examples/qm9/qm9.py:14-95): each molecule's
node feature is the element type, the target is the free energy divided
by the atom count (the ``y[:, 10] / len(x)`` pre-transform), proportional
split, then training. Instead of torch_geometric's downloaded copy, this
driver reads the raw GDB9 ``.xyz`` files natively when present at
``dataset/qm9/raw`` (including the Fortran ``*^`` float notation), and
otherwise generates a deterministic synthetic molecular dataset so the
pipeline runs offline. Bond connectivity is replaced by the framework's
radius graph (Architecture.radius / max_neighbours), the md17-example
pattern.

    python qm9.py [--data dataset/qm9/raw] [--nsamples 1000]
"""

from __future__ import annotations

import argparse
import json
import os
import sys

import numpy as np

_here = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(os.path.dirname(_here)))  # repo root

from hydragnn_tpu.utils.platform import pin_platform_from_env

pin_platform_from_env()  # honor JAX_PLATFORMS even under plugin images

from hydragnn_tpu.api import create_dataloaders, train_with_loaders
from hydragnn_tpu.data.dataset import GraphSample
from hydragnn_tpu.data.formats import SYMBOL_TO_Z
from hydragnn_tpu.data.ingest import prepare_dataset
from hydragnn_tpu.parallel import setup_distributed
from hydragnn_tpu.utils.config import update_config
from hydragnn_tpu.utils.print_utils import setup_log
from hydragnn_tpu.utils.time_utils import print_timers

# scalar properties on the GDB9 comment line after "gdb <idx>":
# [A, B, C, mu, alpha, homo, lumo, gap, r2, zpve, U0, U, H, G, Cv];
# free energy G is index 13 (the reference's y[:, 10] counts from mu).
G_INDEX = 13


def _gdb9_float(tok: str) -> float:
    return float(tok.replace("*^", "e"))


def read_gdb9_xyz(path: str) -> GraphSample:
    with open(path, encoding="utf-8") as f:
        lines = f.read().splitlines()
    n = int(lines[0].split()[0])
    props = [_gdb9_float(t) for t in lines[1].split()[2:]]
    zs = np.zeros(n, dtype=np.int64)
    pos = np.zeros((n, 3), dtype=np.float64)
    for i in range(n):
        parts = lines[2 + i].split()
        zs[i] = SYMBOL_TO_Z[parts[0]]
        pos[i] = [_gdb9_float(parts[1]), _gdb9_float(parts[2]), _gdb9_float(parts[3])]
    return GraphSample(
        x=zs[:, None].astype(np.float64),
        pos=pos.astype(np.float32),
        graph_y=np.asarray([props[G_INDEX]], dtype=np.float64),
    )


def load_qm9_raw(root: str, limit: int) -> list:
    files = sorted(f for f in os.listdir(root) if f.endswith(".xyz"))[:limit]
    return [read_gdb9_xyz(os.path.join(root, f)) for f in files]


def generate_synthetic_qm9(n_samples: int, seed: int = 0) -> list:
    """Random CHNOF clusters with a smooth per-atom free-energy-like
    target (element contribution + pair interaction), so training is
    well-posed offline."""
    rng = np.random.default_rng(seed)
    contrib = {1: -0.5, 6: -38.0, 7: -54.5, 8: -75.0, 9: -99.7}
    samples = []
    for _ in range(n_samples):
        n = int(rng.integers(4, 18))
        zs = rng.choice([1, 6, 7, 8, 9], size=n, p=[0.5, 0.3, 0.08, 0.08, 0.04])
        pos = rng.normal(0, 1.8, (n, 3))
        diff = pos[:, None] - pos[None, :]
        r = np.sqrt((diff**2).sum(-1)) + np.eye(n) * 1e9
        pair = (np.exp(-r / 1.5)).sum() / 2
        g = sum(contrib[int(z)] for z in zs) - 2.0 * pair
        samples.append(
            GraphSample(
                x=zs[:, None].astype(np.float64),
                pos=pos.astype(np.float32),
                graph_y=np.asarray([g], dtype=np.float64),
            )
        )
    return samples


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--data", type=str, default=os.path.join(_here, "dataset/qm9/raw"))
    parser.add_argument("--nsamples", type=int, default=1000,
                        help="sample cap (the reference's qm9_pre_filter)")
    parser.add_argument("--inputfile", type=str, default="qm9.json")
    args = parser.parse_args()

    with open(os.path.join(_here, args.inputfile)) as f:
        config = json.load(f)

    setup_distributed()
    setup_log("qm9_test")

    if os.path.isdir(args.data) and any(
        f.endswith(".xyz") for f in os.listdir(args.data)
    ):
        samples = load_qm9_raw(args.data, args.nsamples)
        print(f"read {len(samples)} GDB9 molecules from {args.data}")
    else:
        print(f"no raw QM9 at {args.data}; generating synthetic molecules")
        samples = generate_synthetic_qm9(args.nsamples)

    train, val, test, mm_g, mm_n = prepare_dataset(samples, config)
    voi = config["NeuralNetwork"]["Variables_of_interest"]
    voi["minmax_graph_feature"] = mm_g.tolist()
    voi["minmax_node_feature"] = mm_n.tolist()
    config = update_config(config, train, val, test)

    loaders = create_dataloaders(train, val, test, config)
    train_with_loaders(config, *loaders)
    print_timers(config["Verbosity"]["level"])


if __name__ == "__main__":
    main()
