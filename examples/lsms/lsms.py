"""LSMS example: FePt free-energy + nodal charge-density/magnetic-moment
multi-task training from LSMS text files.

Mirrors the reference driver (examples/lsms/lsms.py:29-218): rank-0
preprocessing of the raw LSMS directory, compositional stratified split,
container write (HGC replaces ADIOS/pickle), then training from the
container. The reference expects a real FePt_32atoms dataset on disk;
when it is absent this driver generates a synthetic FePt-like dataset in
the same text layout (``Z index x y z charge_density magnetic_moment``,
graph line = free energy) so the full pipeline runs offline.

    python lsms.py --preonly     # (generate if needed) + preprocess + write containers
    python lsms.py               # train from containers
"""

from __future__ import annotations

import argparse
import json
import os
import sys

import numpy as np

_here = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(os.path.dirname(_here)))  # repo root

from hydragnn_tpu.utils.platform import pin_platform_from_env

pin_platform_from_env()  # honor JAX_PLATFORMS even under plugin images

from hydragnn_tpu.api import create_dataloaders, train_with_loaders
from hydragnn_tpu.data.container import ContainerDataset, ContainerWriter
from hydragnn_tpu.data.ingest import load_raw_samples, prepare_dataset
from hydragnn_tpu.parallel import (
    barrier,
    get_comm_size_and_rank,
    nsplit,
    setup_distributed,
)
from hydragnn_tpu.utils.config import get_log_name_config, update_config
from hydragnn_tpu.utils.print_utils import setup_log
from hydragnn_tpu.utils.time_utils import Timer, print_timers

FE, PT = 26, 78


def generate_fept_like(out_dir: str, n_config: int = 200, seed: int = 17) -> None:
    """Synthetic FePt-like LSMS files: 2x2x2 BCC supercells (32 atoms)
    with random Fe/Pt occupation; free energy and nodal charge/moment are
    smooth functions of local composition, so the learning task is
    well-posed (the same idea as tests/deterministic_graph_data.py)."""
    os.makedirs(out_dir, exist_ok=True)
    rng = np.random.default_rng(seed)
    # 2x2x2 conventional BCC cells -> 2 atoms/cell * 16 cells = 32 atoms
    base = np.array([[0.0, 0.0, 0.0], [0.5, 0.5, 0.5]])
    cells = np.array(
        [[i, j, k] for i in range(2) for j in range(2) for k in range(4)], dtype=float
    )
    pos = (cells[:, None, :] + base[None, :, :]).reshape(-1, 3) * 2.87  # Fe a0 (A)
    n = pos.shape[0]
    for c in range(n_config):
        z = np.where(rng.random(n) < rng.uniform(0.2, 0.8), FE, PT).astype(float)
        frac_fe = (z == FE).mean()
        # distance to nearest unlike atom drives the fake local moments
        diff = pos[:, None, :] - pos[None, :, :]
        dist = np.sqrt((diff**2).sum(-1)) + np.eye(n) * 1e9
        unlike = z[:, None] != z[None, :]
        d_unlike = np.where(unlike, dist, np.inf).min(axis=1)
        d_unlike = np.where(np.isfinite(d_unlike), d_unlike, dist.min(axis=1))
        moment = np.where(z == FE, 2.2, 0.35) * np.exp(-d_unlike / 5.0)
        charge = z + 0.05 * np.tanh(moment) + rng.normal(0, 0.01, n)
        free_energy = (
            -4.0 * n * (frac_fe * (1 - frac_fe)) - 0.1 * moment.sum()
            + rng.normal(0, 0.05)
        )
        lines = [f"{free_energy:.10g}"]
        for i in range(n):
            lines.append(
                f"{z[i]:.10g}\t{i}\t{pos[i,0]:.10g}\t{pos[i,1]:.10g}\t{pos[i,2]:.10g}"
                f"\t{charge[i]:.10g}\t{moment[i]:.10g}"
            )
        with open(os.path.join(out_dir, f"out_{c:05d}.txt"), "w") as f:
            f.write("\n".join(lines))


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--preonly", action="store_true", help="preprocess only")
    parser.add_argument("--inputfile", type=str, default="lsms.json")
    parser.add_argument("--nconfig", type=int, default=200,
                        help="synthetic configurations when raw data is absent")
    parser.add_argument("--mode", type=str, default="preload",
                        choices=["mmap", "preload", "shm"])
    args = parser.parse_args()

    with open(os.path.join(_here, args.inputfile)) as f:
        config = json.load(f)

    setup_distributed()
    comm_size, rank = get_comm_size_and_rank()
    setup_log(get_log_name_config(config))

    datasetname = config["Dataset"]["name"]
    raw_dir = os.path.join(_here, config["Dataset"]["path"]["total"])
    container_dir = os.path.join(_here, "dataset", f"{datasetname}.hgc")

    if args.preonly:
        # rank-0 generates (the reference preprocesses rank-0-only,
        # lsms.py:83-85); every rank then runs the deterministic
        # preparation and contributes a disjoint shard, because
        # ContainerWriter.save is a collective op
        if rank == 0 and (not os.path.isdir(raw_dir) or not os.listdir(raw_dir)):
            print(f"raw LSMS data not found at {raw_dir}; generating synthetic")
            generate_fept_like(raw_dir, n_config=args.nconfig)
        barrier("lsms_generate")
        samples = load_raw_samples(config, raw_dir)
        train, val, test, mm_g, mm_n = prepare_dataset(samples, config)
        if rank == 0:
            print(len(samples), len(train), len(val), len(test))
        for name, split in (("trainset", train), ("valset", val), ("testset", test)):
            shard = list(nsplit(split, comm_size))[rank]
            w = ContainerWriter(os.path.join(container_dir, name))
            w.add(shard)
            w.add_global("minmax_graph_feature", mm_g)
            w.add_global("minmax_node_feature", mm_n)
            w.save()
        return

    timer = Timer("load_data")
    timer.start()
    splits = {
        name: ContainerDataset(os.path.join(container_dir, name), mode=args.mode)
        for name in ("trainset", "valset", "testset")
    }
    train, val, test = (splits[k].samples() for k in ("trainset", "valset", "testset"))
    train, val, test = list(train), list(val), list(test)
    mm_g, mm_n = splits["trainset"].minmax()
    timer.stop()

    voi = config["NeuralNetwork"]["Variables_of_interest"]
    voi["minmax_graph_feature"] = mm_g.tolist()
    voi["minmax_node_feature"] = mm_n.tolist()
    config = update_config(config, train, val, test)

    loaders = create_dataloaders(train, val, test, config)
    train_with_loaders(config, *loaders)
    print_timers(config["Verbosity"]["level"])


if __name__ == "__main__":
    main()
