"""EAM example: NiNb solid-solution per-atom energies (and forces) from
AtomEye CFG files, node-level regression with PBC + rotational
invariance.

Mirrors the reference driver (examples/eam/eam.py:29-219): read the CFG
dataset, compositional stratified split, container write (HGC replaces
ADIOS/pickle), then training from the container. The reference expects
the OLCF NiNb dataset (10.13139_OLCF_1890159); when absent, this driver
generates synthetic NiNb FCC supercells with a Finnis-Sinclair-style EAM
potential (per-atom energies + finite-difference forces) in the same CFG
layout, so the full pipeline runs offline.

    python eam.py --preonly [--inputfile NiNb_EAM_energy.json]
    python eam.py           [--inputfile NiNb_EAM_multitask.json]
"""

from __future__ import annotations

import argparse
import json
import os
import sys

import numpy as np

_here = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(os.path.dirname(_here)))  # repo root

from hydragnn_tpu.utils.platform import pin_platform_from_env

pin_platform_from_env()  # honor JAX_PLATFORMS even under plugin images

from hydragnn_tpu.api import create_dataloaders, train_with_loaders
from hydragnn_tpu.data.container import ContainerDataset, ContainerWriter
from hydragnn_tpu.data.ingest import load_raw_samples, prepare_dataset
from hydragnn_tpu.parallel import (
    barrier,
    get_comm_size_and_rank,
    nsplit,
    setup_distributed,
)
from hydragnn_tpu.utils.config import get_log_name_config, update_config
from hydragnn_tpu.utils.print_utils import setup_log
from hydragnn_tpu.utils.time_utils import Timer, print_timers

NI, NB = 28, 41
MASS = {NI: 58.693, NB: 92.906}
SYM = {NI: "Ni", NB: "Nb"}

# Finnis-Sinclair-style pair parameters (A: repulsive, XI: cohesive),
# species-pair keyed; values are plausible, not fitted — the point is a
# smooth, physical target function.
_P = {"A": {(NI, NI): 0.10, (NB, NB): 0.16, (NI, NB): 0.13},
      "XI": {(NI, NI): 1.2, (NB, NB): 1.8, (NI, NB): 1.5},
      "R0": {(NI, NI): 2.49, (NB, NB): 2.86, (NI, NB): 2.67}}


def _pairkey(zi, zj):
    return (min(zi, zj), max(zi, zj))


def _pair_matrices(z: np.ndarray):
    """Vectorized A/XI/R0 lookup tables for a species vector (they depend
    only on z, so compute once per configuration)."""
    is_nb = (z == NB).astype(int)
    kind = is_nb[:, None] + is_nb[None, :]  # 0=NiNi, 1=NiNb, 2=NbNb
    keys = [(NI, NI), (NI, NB), (NB, NB)]
    lut = lambda tbl: np.asarray([tbl[k] for k in keys])[kind]
    return lut(_P["A"]), lut(_P["XI"]), lut(_P["R0"])


def eam_atomic_energies(pos, z, cell, pair=None) -> np.ndarray:
    """E_i = sum_j A*exp(-p(r/r0-1)) - sqrt(sum_j xi^2*exp(-2q(r/r0-1)))
    with minimum-image PBC (Finnis-Sinclair / Gupta form)."""
    n = len(z)
    A, XI, R0 = pair if pair is not None else _pair_matrices(z)
    inv = np.linalg.inv(cell)
    d = pos[:, None, :] - pos[None, :, :]
    # minimum image in fractional space
    frac = d @ inv
    frac -= np.round(frac)
    d = frac @ cell
    r = np.sqrt((d**2).sum(-1)) + np.eye(n) * 1e9
    p, q, rc = 10.0, 2.5, 5.0
    mask = (r < rc).astype(float)
    rep = (A * np.exp(-p * (r / R0 - 1.0)) * mask).sum(axis=1)
    rho = (XI**2 * np.exp(-2.0 * q * (r / R0 - 1.0)) * mask).sum(axis=1)
    return rep - np.sqrt(np.maximum(rho, 1e-12))


def eam_forces(pos, z, cell, h=1e-4):
    """Central finite differences of the total EAM energy."""
    pair = _pair_matrices(z)
    f = np.zeros_like(pos)
    for i in range(len(z)):
        for a in range(3):
            pp, pm = pos.copy(), pos.copy()
            pp[i, a] += h
            pm[i, a] -= h
            f[i, a] = -(eam_atomic_energies(pp, z, cell, pair).sum()
                        - eam_atomic_energies(pm, z, cell, pair).sum()) / (2 * h)
    return f


def write_cfg(path: str, pos, z, cell, atomic_e, forces) -> None:
    """AtomEye extended CFG with aux [c_peratom, fx, fy, fz]."""
    n = len(z)
    frac = pos @ np.linalg.inv(cell)
    lines = [f"Number of particles = {n}", "A = 1.0 Angstrom (basic length-scale)"]
    for i in range(3):
        for j in range(3):
            lines.append(f"H0({i+1},{j+1}) = {cell[i, j]:.8f} A")
    lines += [".NO_VELOCITY.", "entry_count = 7",
              "auxiliary[0] = c_peratom [eV]",
              "auxiliary[1] = fx [eV/A]", "auxiliary[2] = fy [eV/A]",
              "auxiliary[3] = fz [eV/A]"]
    for zs in sorted(set(z.tolist())):
        lines.append(f"{MASS[zs]:.4f}")
        lines.append(SYM[zs])
        for i in np.where(z == zs)[0]:
            lines.append(
                f"{frac[i,0]:.8f} {frac[i,1]:.8f} {frac[i,2]:.8f} "
                f"{atomic_e[i]:.8f} {forces[i,0]:.8f} {forces[i,1]:.8f} {forces[i,2]:.8f}"
            )
    with open(path, "w") as f:
        f.write("\n".join(lines))
    # .bulk sidecar (reference cfgdataset.py bulk pathway): columns are
    # total_energy volume bulk_modulus — bulk modulus is a smooth
    # composition blend (GPa-ish) so the bulk configs have a learnable
    # graph target
    frac_ni = float((z == NI).mean())
    bulk_modulus = 180.0 * frac_ni + 170.0 * (1 - frac_ni) - 25.0 * frac_ni * (1 - frac_ni)
    volume = float(abs(np.linalg.det(cell)))
    with open(os.path.splitext(path)[0] + ".bulk", "w") as f:
        f.write(f"{atomic_e.sum():.8f} {volume:.8f} {bulk_modulus:.8f}\n")


def generate_ninb(out_dir: str, n_config: int = 100, seed: int = 7,
                  num_shards: int = 1, shard: int = 0) -> None:
    os.makedirs(out_dir, exist_ok=True)
    rng = np.random.default_rng(seed + shard)
    # 2x2x2 FCC supercell: 32 atoms
    base = np.array([[0, 0, 0], [0.5, 0.5, 0], [0.5, 0, 0.5], [0, 0.5, 0.5]])
    cells = np.array([[i, j, k] for i in range(2) for j in range(2) for k in range(2)],
                     dtype=float)
    frac = ((cells[:, None, :] + base[None, :, :]).reshape(-1, 3)) / 2.0
    a0 = 3.52 * 2  # 2x2x2 supercell of Ni FCC
    my = list(nsplit(range(n_config), num_shards))[shard]
    for c in my:
        cell = np.eye(3) * a0 * rng.uniform(0.98, 1.02)
        z = np.where(rng.random(len(frac)) < rng.uniform(0.1, 0.9), NI, NB)
        pos = frac @ cell + rng.normal(0, 0.05, (len(frac), 3))
        e = eam_atomic_energies(pos, z, cell)
        f = eam_forces(pos, z, cell)
        write_cfg(os.path.join(out_dir, f"NiNb_{c:05d}.cfg"), pos, z, cell, e, f)


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--preonly", action="store_true")
    parser.add_argument("--inputfile", type=str, default="NiNb_EAM_energy.json")
    parser.add_argument("--nconfig", type=int, default=100,
                        help="synthetic configurations when raw data is absent")
    parser.add_argument("--mode", type=str, default="preload",
                        choices=["mmap", "preload", "shm"])
    args = parser.parse_args()

    with open(os.path.join(_here, args.inputfile)) as f:
        config = json.load(f)

    setup_distributed()
    comm_size, rank = get_comm_size_and_rank()
    setup_log(get_log_name_config(config))

    datasetname = config["Dataset"]["name"]
    raw_dir = os.path.join(_here, config["Dataset"]["path"]["total"])
    # container named per config: the packed targets depend on the
    # config's Variables_of_interest, so different inputfiles must not
    # share a container
    config_stem = os.path.splitext(os.path.basename(args.inputfile))[0]
    container_dir = os.path.join(_here, "dataset", f"{datasetname}_{config_stem}.hgc")

    if args.preonly:
        have_cfg = os.path.isdir(raw_dir) and any(
            f.endswith(".cfg") for f in os.listdir(raw_dir)
        )
        if have_cfg:
            # stale data from an older generator version: the .bulk
            # sidecar must carry [total_energy volume bulk_modulus]
            bulks = sorted(
                f for f in os.listdir(raw_dir) if f.endswith(".bulk")
            )
            if bulks:
                with open(os.path.join(raw_dir, bulks[0])) as f:
                    if len(f.readline().split()) < 3:
                        print("stale .bulk sidecars detected; regenerating dataset")
                        import shutil

                        shutil.rmtree(raw_dir)
                        have_cfg = False
        if not have_cfg:
            print(f"raw CFG data not found at {raw_dir}; generating synthetic NiNb")
            generate_ninb(raw_dir, n_config=args.nconfig,
                          num_shards=comm_size, shard=rank)
        barrier("eam_generate")
        # every rank runs the deterministic preparation and contributes a
        # disjoint shard (ContainerWriter.save is a collective op)
        samples = load_raw_samples(config, raw_dir)
        train, val, test, mm_g, mm_n = prepare_dataset(samples, config)
        if rank == 0:
            print(len(samples), len(train), len(val), len(test))
        for name, split in (("trainset", train), ("valset", val), ("testset", test)):
            shard = list(nsplit(split, comm_size))[rank]
            w = ContainerWriter(os.path.join(container_dir, name))
            w.add(shard)
            w.add_global("minmax_graph_feature", mm_g)
            w.add_global("minmax_node_feature", mm_n)
            w.save()
        return

    timer = Timer("load_data")
    timer.start()
    splits = {
        name: ContainerDataset(os.path.join(container_dir, name), mode=args.mode)
        for name in ("trainset", "valset", "testset")
    }
    train = splits["trainset"].samples()
    val = splits["valset"].samples()
    test = splits["testset"].samples()
    mm_g, mm_n = splits["trainset"].minmax()
    timer.stop()

    voi = config["NeuralNetwork"]["Variables_of_interest"]
    voi["minmax_graph_feature"] = mm_g.tolist()
    voi["minmax_node_feature"] = mm_n.tolist()
    config = update_config(config, train, val, test)

    loaders = create_dataloaders(train, val, test, config)
    train_with_loaders(config, *loaders)
    print_timers(config["Verbosity"]["level"])


if __name__ == "__main__":
    main()
