"""CSCE HOMO-LUMO gap example: single csv split by ratio -> molecular
graphs (native SMILES parser) -> HGC containers -> graph-head training.

Mirrors the reference pipeline (examples/csce/train_gap.py:47-415):
csv rows carry (id, smiles, gap, ...) read as row[1]/row[-2]; the split
is proportional [0.94, 0.02, 0.04]; featurization is sharded across
processes. When the real CSCE csv is absent, a deterministic sample csv
is generated so the pipeline runs offline.

    python train_gap.py --preonly
    python train_gap.py
"""

from __future__ import annotations

import argparse
import csv
import json
import os
import sys

import numpy as np

_here = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(os.path.dirname(_here)))  # repo root

from hydragnn_tpu.utils.platform import pin_platform_from_env

pin_platform_from_env()  # honor JAX_PLATFORMS even under plugin images

from hydragnn_tpu.api import create_dataloaders, train_with_loaders
from hydragnn_tpu.data.container import ContainerDataset, ContainerWriter
from hydragnn_tpu.data.dataset import update_predicted_values
from hydragnn_tpu.data.smiles import (
    generate_graphdata_from_smilestr,
    get_node_attribute_name,
    mol_from_smiles,
)
from hydragnn_tpu.parallel import (
    barrier,
    get_comm_size_and_rank,
    nsplit,
    setup_distributed,
)
from hydragnn_tpu.utils.config import update_config
from hydragnn_tpu.utils.print_utils import iterate_tqdm, setup_log
from hydragnn_tpu.utils.time_utils import Timer, print_timers

# reference element set (examples/csce/train_gap.py:40)
csce_node_types = {"C": 0, "F": 1, "H": 2, "N": 3, "O": 4, "S": 5}

_SAMPLE_SMILES = [
    "C", "CC", "CCC", "CCCC", "CCCCC", "CC(C)C", "CC(C)(C)C",
    "CO", "CCO", "CCCO", "CC(O)C", "OCCO", "COC", "CCOCC",
    "CN", "CCN", "CCCN", "NCCN", "CNC", "CC(C)N",
    "C=C", "CC=C", "C=CC=C", "C#C", "CC#N",
    "CC=O", "CC(=O)C", "CC(=O)O", "CC(=O)N",
    "c1ccccc1", "Cc1ccccc1", "Oc1ccccc1", "Nc1ccccc1", "c1ccncc1",
    "c1ccoc1", "c1ccsc1", "FC(F)F", "CCF", "CS", "CCS", "CSC",
    "C1CCCCC1", "C1CCCC1", "OC1CCCCC1", "C1CCOCC1", "C1CCNCC1",
    "OCC(O)CO", "NCC(=O)O", "CC(N)C(=O)O", "CSCC(N)C(=O)O",
]


def _fake_gap(smiles: str) -> float:
    mol = mol_from_smiles(smiles)
    n_c = sum(a.symbol == "C" for a in mol.atoms)
    n_o = sum(a.symbol == "O" for a in mol.atoms)
    n_arom = sum(a.aromatic for a in mol.atoms)
    n_pi = sum(b.order > 1 for b in mol.bonds)
    return float(np.clip(8.5 - 0.2 * n_c - 0.3 * n_o - 0.4 * n_arom - 0.5 * n_pi,
                         1.0, 10.0))


def make_sample_csv(path: str, seed: int = 43) -> None:
    """CSCE layout: id, smiles, gap, uncertainty (gap = row[-2])."""
    rng = np.random.default_rng(seed)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    rows = []
    i = 0
    for s in _SAMPLE_SMILES:
        for _ in range(6):
            rows.append((i, s, _fake_gap(s), 0.0))
            i += 1
    order = rng.permutation(len(rows))
    with open(path, "w", newline="") as f:
        w = csv.writer(f)
        w.writerow(["id", "smiles", "gap", "uncertainty"])
        w.writerows([rows[j] for j in order])


def datasets_load(datafile, sampling=None, seed=None, frac=(0.94, 0.02, 0.04)):
    """(reference csce_datasets_load, train_gap.py:47-91)"""
    rng = np.random.default_rng(seed)
    smiles_all, values_all = [], []
    with open(datafile) as f:
        reader = csv.reader(f)
        next(reader)
        for row in reader:
            if sampling is not None and rng.random() > sampling:
                continue
            smiles_all.append(row[1])
            values_all.append([float(row[-2])])
    print("Total:", len(smiles_all), len(values_all))
    n = len(smiles_all)
    if n < 3:
        raise SystemExit(
            f"datafile yielded only {n} molecules"
            + (f" at sampling={sampling}" if sampling is not None else "")
            + "; need >= 3 for train/val/test splits"
        )
    # every split must be non-empty for the container write + training:
    # clamp the cut points to 1 <= lo < hi < n
    lo = min(max(int(frac[0] * n), 1), max(n - 2, 1))
    hi = min(max(int((frac[0] + frac[1]) * n), lo + 1), max(n - 1, lo + 1))
    ix = np.split(np.arange(n), [lo, hi])
    return (
        [[smiles_all[i] for i in part] for part in ix],
        [np.asarray([values_all[i] for i in part], dtype=np.float32) for part in ix],
        float(np.mean(values_all)),
        float(np.std(values_all)),
    )


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--preonly", action="store_true")
    parser.add_argument("--inputfile", type=str, default="csce_gap.json")
    parser.add_argument("--sampling", type=float, default=None)
    parser.add_argument("--mode", type=str, default="preload",
                        choices=["mmap", "preload", "shm"])
    args = parser.parse_args()

    with open(os.path.join(_here, args.inputfile)) as f:
        config = json.load(f)
    verbosity = config["Verbosity"]["level"]
    var_config = config["NeuralNetwork"]["Variables_of_interest"]

    setup_distributed()
    comm_size, rank = get_comm_size_and_rank()
    setup_log("csce_gap_eV_fullx")

    datafile = os.path.join(_here, "dataset", "csce_gap.csv")
    container_dir = os.path.join(_here, "dataset", "csce_gap.hgc")

    node_attr_names, node_attr_dims = get_node_attribute_name(csce_node_types)
    config["Dataset"] = {
        "name": "csce_gap",
        "format": "HGC",
        "node_features": {"name": node_attr_names, "dim": node_attr_dims,
                          "column_index": list(range(len(node_attr_names)))},
        "graph_features": {"name": ["gap"], "dim": [1], "column_index": [0]},
    }

    if args.preonly:
        if rank == 0 and not os.path.exists(datafile):
            print(f"{datafile} not found; writing deterministic sample csv")
            make_sample_csv(datafile)
        barrier("csce_csv")
        smiles_sets, values_sets, ymean, ystd = datasets_load(
            datafile, sampling=args.sampling, seed=43
        )
        for smileset, valueset, setname in zip(
            smiles_sets, values_sets, ("trainset", "valset", "testset")
        ):
            rx = list(nsplit(range(len(smileset)), comm_size))[rank]
            samples = []
            for i in iterate_tqdm(range(rx.start, rx.stop), verbosity):
                samples.append(
                    generate_graphdata_from_smilestr(
                        smileset[i], valueset[i], csce_node_types
                    )
                )
            update_predicted_values(
                samples, var_config["type"], var_config["output_index"],
                var_config["output_names"], [1], node_attr_dims,
            )
            w = ContainerWriter(os.path.join(container_dir, setname))
            w.add(samples)
            w.add_global("ymean", [ymean])
            w.add_global("ystd", [ystd])
            w.save()
            print(f"rank {rank}: {setname} {len(samples)} molecules")
        return

    timer = Timer("load_data")
    timer.start()
    splits = [
        ContainerDataset(os.path.join(container_dir, n), mode=args.mode).samples()
        for n in ("trainset", "valset", "testset")
    ]
    train, val, test = splits
    timer.stop()

    config = update_config(config, train, val, test)
    loaders = create_dataloaders(train, val, test, config)
    train_with_loaders(config, *loaders)
    print_timers(verbosity)


if __name__ == "__main__":
    main()
