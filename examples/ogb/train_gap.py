"""OGB (PCQM4Mv2-style) HOMO-LUMO gap example: SMILES csv ->
molecular-graph featurization (native parser) -> HGC containers ->
graph-head training.

Mirrors the reference pipeline (examples/ogb/train_gap.py:238-428): the
csv rows carry (smiles, split, gap); featurization is sharded across
processes with ``nsplit``; --preonly writes the parallel containers
(HGC replaces ADIOS/pickle) and training reads them back. The reference
expects the real pcqm4m_gap.csv; when absent a small deterministic
sample csv is generated so the pipeline runs offline.

    python train_gap.py --preonly
    python train_gap.py
"""

from __future__ import annotations

import argparse
import csv
import json
import os
import sys

import numpy as np

_here = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(os.path.dirname(_here)))  # repo root

from hydragnn_tpu.utils.platform import pin_platform_from_env

pin_platform_from_env()  # honor JAX_PLATFORMS even under plugin images

from hydragnn_tpu.api import create_dataloaders, train_with_loaders
from hydragnn_tpu.data.container import ContainerDataset, ContainerWriter
from hydragnn_tpu.data.dataset import update_predicted_values
from hydragnn_tpu.data.smiles import (
    generate_graphdata_from_smilestr,
    get_node_attribute_name,
    mol_from_smiles,
)
from hydragnn_tpu.parallel import (
    barrier,
    get_comm_size_and_rank,
    nsplit,
    setup_distributed,
)
from hydragnn_tpu.utils.config import update_config
from hydragnn_tpu.utils.print_utils import iterate_tqdm, setup_log
from hydragnn_tpu.utils.time_utils import Timer, print_timers

# reference element set (examples/ogb/train_gap.py:40-72)
ogb_node_types = {
    "H": 0, "B": 1, "C": 2, "N": 3, "O": 4, "F": 5, "Si": 6, "P": 7, "S": 8,
    "Cl": 9, "Ca": 10, "Ge": 11, "As": 12, "Se": 13, "Br": 14, "I": 15,
    "Mg": 16, "Ti": 17, "Ga": 18, "Zn": 19, "Ar": 20, "Be": 21, "He": 22,
    "Al": 23, "Kr": 24, "V": 25, "Na": 26, "Li": 27, "Cu": 28, "Ne": 29,
    "Ni": 30,
}

_SAMPLE_SMILES = [
    "C", "CC", "CCC", "CCCC", "CCCCC", "CCCCCC", "CC(C)C", "CC(C)(C)C",
    "CO", "CCO", "CCCO", "CC(O)C", "OCCO", "CCOC", "COC", "CCOCC",
    "CN", "CCN", "CCCN", "CC(N)C", "NCCN", "CNC", "CCNCC", "CC(C)N",
    "C=C", "CC=C", "C=CC=C", "CC=CC", "C#C", "CC#C", "CC#N", "C#N",
    "C=O", "CC=O", "CCC=O", "CC(=O)C", "CC(=O)O", "CCC(=O)O", "CC(=O)N",
    "c1ccccc1", "Cc1ccccc1", "CCc1ccccc1", "Oc1ccccc1", "Nc1ccccc1",
    "c1ccncc1", "c1ccoc1", "c1ccsc1", "Cc1ccncc1", "Cc1ccco1",
    "FC(F)F", "CCF", "CCCl", "CCBr", "CC(F)C", "FCC(F)F",
    "CS", "CCS", "CSC", "CC(=O)S", "CCSCC",
    "C1CCCCC1", "C1CCCC1", "C1CCC1", "CC1CCCCC1", "OC1CCCCC1",
    "NC1CCCCC1", "C1CCOCC1", "C1CCNCC1", "C1CCSCC1",
    "CC(C)CC", "CCC(C)C", "CCCC(C)C", "CC(C)CO", "CC(C)CN",
    "OCC(O)CO", "NCC(=O)O", "CC(N)C(=O)O", "CSCC(N)C(=O)O",
]


def _fake_gap(smiles: str) -> float:
    """Deterministic gap-like target from composition (eV-ish scale)."""
    mol = mol_from_smiles(smiles)
    n_c = sum(a.symbol == "C" for a in mol.atoms)
    n_o = sum(a.symbol == "O" for a in mol.atoms)
    n_n = sum(a.symbol == "N" for a in mol.atoms)
    n_arom = sum(a.aromatic for a in mol.atoms)
    n_pi = sum(b.order > 1 for b in mol.bonds)
    return float(np.clip(9.0 - 0.25 * n_c - 0.35 * n_o - 0.2 * n_n
                         - 0.45 * n_arom - 0.5 * n_pi, 1.0, 10.0))


def make_sample_csv(path: str, seed: int = 43) -> None:
    """pcqm4m_gap.csv layout: smiles, split, gap."""
    rng = np.random.default_rng(seed)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    rows = []
    for s in _SAMPLE_SMILES:
        for _ in range(4):  # repeat to give the tiny set some bulk
            split = rng.choice(["train", "val", "test"], p=[0.8, 0.1, 0.1])
            rows.append((s, split, _fake_gap(s)))
    with open(path, "w", newline="") as f:
        w = csv.writer(f)
        w.writerow(["smiles", "set", "gap"])
        w.writerows(rows)


def datasets_load(datafile: str, sampling=None, seed=None):
    """(reference ogb_datasets_load, train_gap.py:80-113)"""
    rng = np.random.default_rng(seed)
    smiles = {"train": [], "val": [], "test": []}
    values = {"train": [], "val": [], "test": []}
    first = {}  # per-split fallback so heavy sampling can't empty a split
    with open(datafile) as f:
        reader = csv.reader(f)
        next(reader)
        # one rng draw per row in file order (seed-for-seed parity with
        # the reference sampling, reference ogb train_gap.py:80-113);
        # memory stays proportional to the KEPT sample
        for row in reader:
            split, s, v = row[1], row[0], [float(row[-1])]
            first.setdefault(split, (s, v))
            if sampling is not None and rng.random() > sampling:
                continue
            smiles[split].append(s)
            values[split].append(v)
    for split, (s, v) in first.items():
        if not smiles[split]:
            smiles[split].append(s)
            values[split].append(v)
    return ([smiles[k] for k in ("train", "val", "test")],
            [np.asarray(values[k], dtype=np.float32) for k in ("train", "val", "test")])


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--preonly", action="store_true")
    parser.add_argument("--inputfile", type=str, default="ogb_gap.json")
    parser.add_argument("--sampling", type=float, default=None)
    parser.add_argument("--mode", type=str, default="preload",
                        choices=["mmap", "preload", "shm"])
    args = parser.parse_args()

    with open(os.path.join(_here, args.inputfile)) as f:
        config = json.load(f)
    verbosity = config["Verbosity"]["level"]
    var_config = config["NeuralNetwork"]["Variables_of_interest"]

    setup_distributed()
    comm_size, rank = get_comm_size_and_rank()
    setup_log("ogb_gap_eV_fullx")

    datafile = os.path.join(_here, "dataset", "pcqm4m_gap.csv")
    container_dir = os.path.join(_here, "dataset", "ogb_gap.hgc")

    node_attr_names, node_attr_dims = get_node_attribute_name(ogb_node_types)
    config["Dataset"] = {
        "name": "ogb_gap",
        "format": "HGC",
        "node_features": {"name": node_attr_names, "dim": node_attr_dims,
                          "column_index": list(range(len(node_attr_names)))},
        "graph_features": {"name": ["gap"], "dim": [1], "column_index": [0]},
    }

    if args.preonly:
        if rank == 0 and not os.path.exists(datafile):
            print(f"{datafile} not found; writing deterministic sample csv")
            make_sample_csv(datafile)
        barrier("ogb_csv")
        smiles_sets, values_sets = datasets_load(datafile, sampling=args.sampling, seed=43)
        setnames = ["trainset", "valset", "testset"]
        for smileset, valueset, setname in zip(smiles_sets, values_sets, setnames):
            rx = list(nsplit(range(len(smileset)), comm_size))[rank]
            samples = []
            for i in iterate_tqdm(range(rx.start, rx.stop), verbosity):
                samples.append(
                    generate_graphdata_from_smilestr(
                        smileset[i], valueset[i], ogb_node_types
                    )
                )
            update_predicted_values(
                samples, var_config["type"], var_config["output_index"],
                var_config["output_names"], [1], node_attr_dims,
            )
            w = ContainerWriter(os.path.join(container_dir, setname))
            w.add(samples)
            w.save()
            print(f"rank {rank}: {setname} {len(samples)} molecules")
        return

    timer = Timer("load_data")
    timer.start()
    splits = [
        ContainerDataset(os.path.join(container_dir, n), mode=args.mode).samples()
        for n in ("trainset", "valset", "testset")
    ]
    train, val, test = splits
    timer.stop()

    config = update_config(config, train, val, test)
    loaders = create_dataloaders(train, val, test, config)
    train_with_loaders(config, *loaders)
    print_timers(verbosity)


if __name__ == "__main__":
    main()
