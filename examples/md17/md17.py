"""MD17 example: molecular-dynamics energy regression on uracil
trajectories (graph head) with in-config radius-graph construction.

Mirrors the reference driver (examples/md17/md17.py:14-104): node
feature = element type, target = energy / atom count, ~25% random
subsample of the trajectory, radius-graph edges from the Architecture
config, proportional split, then training. Instead of torch_geometric's
downloaded npz, this driver reads an MD17-format ``.npz`` natively when
present (keys ``R`` [m,n,3], ``z`` [n], ``E`` [m], ``F`` [m,n,3]) and
otherwise generates a synthetic harmonic uracil-like trajectory so the
pipeline runs offline.

    python md17.py [--data dataset/md17/md17_uracil.npz]
"""

from __future__ import annotations

import argparse
import json
import os
import sys

import numpy as np

_here = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(os.path.dirname(_here)))  # repo root

from hydragnn_tpu.utils.platform import pin_platform_from_env

pin_platform_from_env()  # honor JAX_PLATFORMS even under plugin images

from hydragnn_tpu.api import create_dataloaders, train_with_loaders
from hydragnn_tpu.data.dataset import GraphSample
from hydragnn_tpu.data.ingest import prepare_dataset
from hydragnn_tpu.parallel import setup_distributed
from hydragnn_tpu.utils.config import update_config
from hydragnn_tpu.utils.print_utils import setup_log
from hydragnn_tpu.utils.time_utils import print_timers

# idealized planar uracil (C4H4N2O2), close enough for a synthetic
# harmonic trajectory around it
_URACIL_Z = np.array([7, 6, 7, 6, 6, 6, 8, 8, 1, 1, 1, 1])
_URACIL_POS = np.array([
    [0.00, 1.39, 0.0], [1.20, 0.69, 0.0], [1.20, -0.69, 0.0],
    [0.00, -1.39, 0.0], [-1.20, -0.69, 0.0], [-1.20, 0.69, 0.0],
    [2.30, 1.30, 0.0], [0.00, -2.60, 0.0],
    [-0.05, 2.40, 0.0], [2.10, -1.20, 0.0], [-2.10, -1.20, 0.0],
    [-2.15, 1.25, 0.0],
])


def load_md17_npz(path: str) -> tuple:
    data = np.load(path)
    return data["R"], data["z"], data["E"].reshape(-1)


def generate_synthetic_md17(n_frames: int = 4000, seed: int = 0) -> tuple:
    """Harmonic fluctuations around the uracil geometry: E = 0.5 k |dx|^2
    (per-frame), a well-posed stand-in for the real trajectory."""
    rng = np.random.default_rng(seed)
    n = len(_URACIL_Z)
    disp = rng.normal(0, 0.08, (n_frames, n, 3))
    R = _URACIL_POS[None] + disp
    k = 55.0
    E = -259640.0 + 0.5 * k * (disp**2).sum(axis=(1, 2))
    return R, _URACIL_Z, E


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument(
        "--data", type=str,
        default=os.path.join(_here, "dataset/md17/md17_uracil.npz"),
    )
    parser.add_argument("--subsample", type=float, default=0.25,
                        help="trajectory keep fraction (reference md17_pre_filter)")
    parser.add_argument("--maxframes", type=int, default=1000)
    parser.add_argument("--inputfile", type=str, default="md17.json")
    args = parser.parse_args()

    with open(os.path.join(_here, args.inputfile)) as f:
        config = json.load(f)

    setup_distributed()
    setup_log("md17_test")

    if os.path.isfile(args.data):
        R, z, E = load_md17_npz(args.data)
        print(f"read {len(E)} MD17 frames from {args.data}")
    else:
        print(f"no MD17 npz at {args.data}; generating synthetic uracil trajectory")
        R, z, E = generate_synthetic_md17()

    rng = np.random.default_rng(25)
    keep = np.where(rng.random(len(E)) < args.subsample)[0][: args.maxframes]
    samples = [
        GraphSample(
            x=np.asarray(z, dtype=np.float64)[:, None],
            pos=R[i].astype(np.float32),
            graph_y=np.asarray([E[i]], dtype=np.float64),
        )
        for i in keep
    ]

    train, val, test, mm_g, mm_n = prepare_dataset(samples, config)
    voi = config["NeuralNetwork"]["Variables_of_interest"]
    voi["minmax_graph_feature"] = mm_g.tolist()
    voi["minmax_node_feature"] = mm_n.tolist()
    config = update_config(config, train, val, test)

    loaders = create_dataloaders(train, val, test, config)
    train_with_loaders(config, *loaders)
    print_timers(config["Verbosity"]["level"])


if __name__ == "__main__":
    main()
