"""3-D Ising-model dataset generator (reference behavior:
examples/ising_model/create_configurations.py:29-136, rewritten
vectorized).

Enumerates spin configurations of an L x L x L periodic lattice by
number-of-down-spins composition; compositions with more than
``histogram_cutoff`` possible configurations are randomly subsampled,
smaller ones are enumerated exhaustively (distinct multiset
permutations). The dimensionless energy uses the reference's convention
(create_configurations.py:53-72): per-site neighbour sum includes the
six periodic nearest neighbours plus the site itself, and the total is
divided by 6. A nonlinear spin function and random spin-magnitude
scaling extend the classic model.

Files are written in the LSMS text layout our reader consumes
(hydragnn_tpu/data/lsms.py: row = ``feature index x y z out...``), i.e.
``config_value site_index x y z spin`` — node features are selected by
column_index from the JSON config.
"""

from __future__ import annotations

import math
import os
from typing import Callable, Optional

import numpy as np


def ising_energy_and_features(
    config: np.ndarray,
    spin_function: Callable[[np.ndarray], np.ndarray] = lambda x: x,
    scale_spin: bool = False,
    rng: Optional[np.random.Generator] = None,
):
    """Energy + per-site features for one L^3 configuration of +-1 spins.

    Returns (total_energy, features[L^3, 5]) with feature columns
    [config, x, y, z, spin], sites ordered x-major (z fastest).
    """
    L = config.shape[0]
    if scale_spin:
        rng = rng or np.random.default_rng()
        config = config * rng.random((L, L, L))
    spin = spin_function(config)

    # six periodic nearest neighbours + the site itself (reference
    # create_configurations.py:55-63 counts spin[x,y,z] once in nb)
    nb = spin.copy()
    for axis in range(3):
        nb += np.roll(spin, 1, axis=axis) + np.roll(spin, -1, axis=axis)
    total_energy = float(-(nb * spin).sum() / 6.0)

    xs, ys, zs = np.meshgrid(np.arange(L), np.arange(L), np.arange(L), indexing="ij")
    features = np.stack(
        [
            config.reshape(-1),
            xs.reshape(-1).astype(np.float64),
            ys.reshape(-1).astype(np.float64),
            zs.reshape(-1).astype(np.float64),
            spin.reshape(-1),
        ],
        axis=1,
    )
    return total_energy, features


def distinct_permutations(items: np.ndarray):
    """Lexicographic distinct permutations of a multiset (replaces
    sympy's multiset_permutations; standard next-permutation algorithm)."""
    a = np.sort(np.asarray(items))[::-1][::-1].copy()  # ascending
    n = len(a)
    while True:
        yield a.copy()
        # find rightmost i with a[i] < a[i+1]
        i = n - 2
        while i >= 0 and a[i] >= a[i + 1]:
            i -= 1
        if i < 0:
            return
        j = n - 1
        while a[j] <= a[i]:
            j -= 1
        a[i], a[j] = a[j], a[i]
        a[i + 1 :] = a[i + 1 :][::-1]


def write_ising_file(total_energy: float, features: np.ndarray, path: str) -> None:
    """LSMS row layout: ``config site_index x y z spin``."""
    lines = [f"{total_energy:.10g}"]
    for i in range(features.shape[0]):
        c, x, y, z, s = features[i]
        lines.append(f"{c:.10g}\t{i}\t{x:.10g}\t{y:.10g}\t{z:.10g}\t{s:.10g}")
    with open(path, "w") as f:
        f.write("\n".join(lines))


def create_dataset(
    L: int,
    histogram_cutoff: int,
    out_dir: str,
    spin_function: Callable = lambda x: x,
    scale_spin: bool = False,
    seed: int = 0,
    num_shards: int = 1,
    shard: int = 0,
    compositions=None,
) -> int:
    """Generate the sharded dataset; shard s handles every composition
    (num_downs value) assigned to it (the reference shards the
    composition loop across MPI ranks, train_ising.py:63-108). Returns
    the number of files written by this shard."""
    os.makedirs(out_dir, exist_ok=True)
    rng = np.random.default_rng(seed + shard)
    n_sites = L**3
    if compositions is None:
        from hydragnn_tpu.parallel import nsplit

        compositions = list(nsplit(range(n_sites), num_shards))[shard]

    written = 0
    for num_downs in compositions:
        primal = np.ones(n_sites)
        primal[:num_downs] = -1.0
        prefix = f"output_{num_downs}_"
        if math.comb(n_sites, num_downs) > histogram_cutoff:
            configs = (
                rng.permutation(primal).reshape(L, L, L)
                for _ in range(histogram_cutoff)
            )
        else:
            configs = (p.reshape(L, L, L) for p in distinct_permutations(primal))
        for count, config in enumerate(configs):
            e, feats = ising_energy_and_features(config, spin_function, scale_spin, rng)
            write_ising_file(e, feats, os.path.join(out_dir, f"{prefix}{count}.txt"))
            written += 1
    return written


if __name__ == "__main__":
    out = os.path.join(os.path.dirname(__file__), "dataset", "ising_model")
    # sine spin function + randomized magnitudes: the reference's
    # nonlinear extension (create_configurations.py:124-136)
    n = create_dataset(
        L=3,
        histogram_cutoff=1000,
        out_dir=out,
        spin_function=lambda x: np.sin(np.pi * x / 2),
        scale_spin=True,
    )
    print(f"wrote {n} configurations to {out}")
