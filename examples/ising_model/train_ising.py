"""Ising-model example: sharded data generation -> HGC container ->
multi-task (graph energy + node spin) training.

Mirrors the reference pipeline (examples/ising_model/train_ising.py:
63-265): generate configurations sharded across processes, read the raw
text dataset, split train/val/test, save to the parallel container
(ADIOS-equivalent: HGC), then train from the container. Run:

    python train_ising.py --preonly      # generate + write containers
    python train_ising.py                # train from containers
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys

import numpy as np

_here = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, _here)
sys.path.insert(0, os.path.dirname(os.path.dirname(_here)))  # repo root (no-install runs)

from hydragnn_tpu.utils.platform import pin_platform_from_env

pin_platform_from_env()  # honor JAX_PLATFORMS even under plugin images
from create_configurations import create_dataset

import hydragnn_tpu
from hydragnn_tpu.api import create_dataloaders, train_with_loaders
from hydragnn_tpu.data.container import ContainerDataset, ContainerWriter
from hydragnn_tpu.data.ingest import load_raw_samples, prepare_dataset
from hydragnn_tpu.parallel import (
    barrier,
    get_comm_size_and_rank,
    nsplit,
    setup_distributed,
)
from hydragnn_tpu.utils.config import update_config
from hydragnn_tpu.utils.print_utils import setup_log
from hydragnn_tpu.utils.time_utils import Timer, print_timers


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--preonly", action="store_true", help="preprocess only")
    parser.add_argument("--natom", type=int, default=3, help="atoms per dimension")
    parser.add_argument(
        "--cutoff", type=int, default=1000, help="configurational histogram cutoff"
    )
    parser.add_argument("--inputfile", type=str, default="ising_model.json")
    parser.add_argument("--mode", type=str, default="preload",
                        choices=["mmap", "preload", "shm"],
                        help="container read mode")
    args = parser.parse_args()

    dirpwd = os.path.dirname(os.path.abspath(__file__))
    with open(os.path.join(dirpwd, args.inputfile)) as f:
        config = json.load(f)

    setup_distributed()
    comm_size, rank = get_comm_size_and_rank()

    modelname = f"ising_model_{args.natom}_{args.cutoff}"
    raw_dir = os.path.join(dirpwd, "dataset", modelname)
    container_dir = os.path.join(dirpwd, "dataset", f"{modelname}.hgc")

    if args.preonly:
        if rank == 0 and os.path.exists(raw_dir):
            shutil.rmtree(raw_dir)
        barrier("ising_rmtree")
        # sine spin function + randomized magnitudes (the reference's
        # nonlinear extension, train_ising.py:205-216); composition loop
        # sharded across processes
        n = create_dataset(
            L=args.natom,
            histogram_cutoff=args.cutoff,
            out_dir=raw_dir,
            spin_function=lambda x: np.sin(np.pi * x / 2),
            scale_spin=True,
            num_shards=comm_size,
            shard=rank,
        )
        print(f"rank {rank}: generated {n} configurations")
        barrier("ising_generate")

        # every rank runs the (deterministic) full preparation, then
        # contributes a disjoint shard of each split to the collective
        # container save (ContainerWriter.save is a collective op)
        config["Dataset"]["path"]["total"] = raw_dir
        samples = load_raw_samples(config, raw_dir)
        train, val, test, mm_g, mm_n = prepare_dataset(samples, config)
        print(len(samples), len(train), len(val), len(test))

        for name, split in (("trainset", train), ("valset", val), ("testset", test)):
            shard = list(nsplit(split, comm_size))[rank]
            writer = ContainerWriter(os.path.join(container_dir, name))
            writer.add(shard)
            writer.add_global("minmax_graph_feature", mm_g)
            writer.add_global("minmax_node_feature", mm_n)
            writer.save()
        return

    timer = Timer("load_data")
    timer.start()
    splits = {
        name: ContainerDataset(os.path.join(container_dir, name), mode=args.mode)
        for name in ("trainset", "valset", "testset")
    }
    train = splits["trainset"].samples()
    val = splits["valset"].samples()
    test = splits["testset"].samples()
    mm_g, mm_n = splits["trainset"].minmax()
    timer.stop()

    voi = config["NeuralNetwork"]["Variables_of_interest"]
    voi["minmax_graph_feature"] = mm_g.tolist()
    voi["minmax_node_feature"] = mm_n.tolist()
    config = update_config(config, train, val, test)

    setup_log("ising_model_test")
    loaders = create_dataloaders(train, val, test, config)
    train_with_loaders(config, *loaders)
    print_timers(config["Verbosity"]["level"])


if __name__ == "__main__":
    main()
