"""Giant-graph training demo: ONE graph too large for sensible
single-batch data parallelism, trained with its edge set sharded over
the device mesh.

The reference cannot partition a single graph across ranks — its
large-graph story is data-side only (SURVEY §5: out-of-core ADIOS
reads, DDStore fetches of whole graphs). This example exercises the
TPU-native headroom beyond that parity point (docs/DESIGN.md §3,
hydragnn_tpu/parallel/edge_sharded.py): a ~120k-node periodic cubic
lattice (6-neighbor adjacency, ~720k directed edges) is placed with
``place_giant_batch`` — edge arrays sharded ``P(data)``, node arrays
replicated — and a PLAIN jitted train step is partitioned by XLA's
SPMD pass: each device computes messages for its own edge shard, the
partial-aggregate all-reduce rides ICI, and the backward pass gets the
matching collectives automatically.

Memory accounting: per-device edge-buffer residency is O(E/D) — the
script asserts each edge leaf's addressable shard holds exactly
rows/D of the global array and prints the bytes.

Run on the virtual CPU mesh:
  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      python examples/giant_graph/train_giant.py --nx 50 --ny 50 --nz 48

The node-level target is closed-form (y_i = tanh of the neighbor-count-
normalized feature sum), so the loss must drop within a few steps.
"""

from __future__ import annotations

import argparse
import os
import sys

import numpy as np

_here = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(os.path.dirname(_here)))  # repo root


def build_lattice_graph(nx: int, ny: int, nz: int, seed: int = 0):
    """Periodic cubic lattice: N = nx*ny*nz nodes, 6 directed edges per
    node (+x,-x,+y,-y,+z,-z neighbors) built by pure index arithmetic —
    no neighbor search needed at this scale."""
    n = nx * ny * nz
    ids = np.arange(n, dtype=np.int32)
    ix = ids % nx
    iy = (ids // nx) % ny
    iz = ids // (nx * ny)

    def nid(x, y, z):
        return (x % nx) + (y % ny) * nx + (z % nz) * nx * ny

    neighbors = [
        nid(ix + 1, iy, iz), nid(ix - 1, iy, iz),
        nid(ix, iy + 1, iz), nid(ix, iy - 1, iz),
        nid(ix, iy, iz + 1), nid(ix, iy, iz - 1),
    ]
    senders = np.concatenate([nb.astype(np.int32) for nb in neighbors])
    receivers = np.concatenate([ids] * 6).astype(np.int32)

    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, 4)).astype(np.float32)
    # closed-form local target: learnable by 2 rounds of message passing
    neigh_sum = np.zeros((n, 4), np.float32)
    np.add.at(neigh_sum, receivers, x[senders])
    y = np.tanh(neigh_sum.mean(axis=1, keepdims=True) / 6.0).astype(np.float32)
    return x, senders, receivers, y


def build_giant_problem(nx: int, ny: int, nz: int, hidden: int, n_devices: int):
    """(model, variables, placed_batch, mesh) for the sharded step."""
    from hydragnn_tpu.graph import batch_graphs
    from hydragnn_tpu.models import ModelConfig, create_model
    from hydragnn_tpu.parallel import make_mesh
    from hydragnn_tpu.parallel.edge_sharded import place_giant_batch

    x, senders, receivers, y = build_lattice_graph(nx, ny, nz)
    n, e = x.shape[0], senders.shape[0]
    g = {
        "x": x,
        "senders": senders,
        "receivers": receivers,
        "node_targets": {"y": y},
    }
    batch = batch_graphs(
        [g],
        n_node_pad=n + 8,
        n_edge_pad=((e + n_devices - 1) // n_devices) * n_devices,
        n_graph_pad=2,
    )
    cfg = ModelConfig(
        model_type="GIN",
        input_dim=4,
        hidden_dim=hidden,
        output_dim=(1,),
        output_type=("node",),
        output_names=("y",),
        task_weights=(1.0,),
        num_conv_layers=2,
        node_num_headlayers=2,
        node_dim_headlayers=(hidden, hidden),
        node_head_type="mlp",
    )
    model, variables = create_model(cfg, batch)
    mesh = make_mesh(n_devices)
    placed = place_giant_batch(mesh, batch)
    return model, variables, placed, mesh


def check_edge_residency(placed, n_devices: int) -> dict:
    """Assert O(E/D) per-device edge residency; return the accounting."""
    acct = {}
    for name in ("senders", "receivers", "edge_mask"):
        arr = getattr(placed, name)
        shard_rows = arr.addressable_shards[0].data.shape[0]
        assert shard_rows * n_devices == arr.shape[0], (
            name, shard_rows, arr.shape)
        acct[name] = {
            "global_rows": int(arr.shape[0]),
            "rows_per_device": int(shard_rows),
            "bytes_per_device": int(arr.addressable_shards[0].data.nbytes),
        }
    # node features stay replicated: full rows on every device
    assert placed.nodes.addressable_shards[0].data.shape[0] == placed.nodes.shape[0]
    return acct


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--nx", type=int, default=50)
    parser.add_argument("--ny", type=int, default=50)
    parser.add_argument("--nz", type=int, default=48)
    parser.add_argument("--hidden", type=int, default=32)
    parser.add_argument("--steps", type=int, default=8)
    parser.add_argument("--lr", type=float, default=0.02)
    args = parser.parse_args(argv)

    from hydragnn_tpu.utils.platform import pin_platform_from_env

    pin_platform_from_env()
    import jax

    from hydragnn_tpu.train import create_train_state, make_train_step, select_optimizer

    n_devices = len(jax.devices())
    model, variables, placed, mesh = build_giant_problem(
        args.nx, args.ny, args.nz, args.hidden, n_devices
    )
    n = placed.nodes.shape[0]
    e = placed.senders.shape[0]
    print(f"giant graph: {n} nodes, {e} edges, mesh of {n_devices} devices")

    acct = check_edge_residency(placed, n_devices)
    for k, v in acct.items():
        print(
            f"  {k}: {v['global_rows']} rows -> {v['rows_per_device']}/device "
            f"({v['bytes_per_device']} bytes/device)  [O(E/D)]"
        )

    tx = select_optimizer({"Optimizer": {"type": "AdamW", "learning_rate": args.lr}})
    state = create_train_state(variables, tx, seed=0)
    step = make_train_step(model, tx)
    losses = []
    for i in range(args.steps):
        state, loss, _ = step(state, placed)
        losses.append(float(np.asarray(loss)))  # D2H: real sync
        print(f"step {i}: loss {losses[-1]:.6f}")
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0], "loss did not decrease"
    print("giant-graph sharded training OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
